"""jit.save / jit.load — deployable program serialization.

TPU-native equivalent of the reference's jit save/load (reference:
python/paddle/jit/api.py ``save``/``load`` → TranslatedLayer; C++
jit::Layer paddle/fluid/jit/layer.h). The serialized artifact is
(a) the state dict (params+buffers) and (b) a ``jax.export`` StableHLO
blob per cached input signature — the portable XLA program format, the
role ProgramDesc+params files play for the reference's AnalysisPredictor.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .static_function import StaticFunction, to_static

__all__ = ["save", "load", "TranslatedLayer"]


def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer (or StaticFunction-wrapped Layer) for deployment."""
    from ..nn import Layer
    from ..static.input_spec import InputSpec

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")

    # 1. params + buffers
    state = {k: np.asarray(v._data) for k, v in layer.state_dict().items()}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)

    # 2. exported StableHLO forward (needs input_spec to know the signature)
    exported_blobs = []
    if input_spec is not None:
        layer.eval()
        params = [p for _, p in layer.named_parameters()]
        buffers = [b for _, b in layer.named_buffers()]

        def pure_forward(param_arrays, buffer_arrays, *arg_arrays):
            from .static_function import _SwappedState
            from ..core import engine

            with _SwappedState(params + buffers,
                               list(param_arrays) + list(buffer_arrays)), \
                    engine.no_grad():
                out = layer(*[Tensor(a) for a in arg_arrays])
            outs = out if isinstance(out, (list, tuple)) else (out,)
            return tuple(o._data for o in outs)

        arg_shapes = []
        for spec in input_spec:
            if isinstance(spec, Tensor):
                spec = InputSpec.from_tensor(spec)
            shape = tuple(1 if s in (-1, None) else s for s in spec.shape)
            arg_shapes.append(
                jax.ShapeDtypeStruct(shape, spec.dtype.np_dtype))
        p_shapes = [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
                    for p in params]
        b_shapes = [jax.ShapeDtypeStruct(b._data.shape, b._data.dtype)
                    for b in buffers]
        from jax import export as jexport

        try:
            exp = jexport.export(jax.jit(pure_forward))(
                p_shapes, b_shapes, *arg_shapes)
            blob = exp.serialize()
        except Exception as e:
            if "callback" in str(e).lower():
                raise RuntimeError(
                    "jit.save cannot serialize a model that calls a "
                    "HOST custom op (a C++ kernel bridged via "
                    "jax.pure_callback — e.g. "
                    "cpp_extension.CustomOpModule.elementwise_op): the "
                    "StableHLO artifact would reference a host function "
                    "that does not exist at load time. Re-implement the "
                    "op as a device kernel (jnp/Pallas) via "
                    "cpp_extension.register_custom_op, or deploy the "
                    "model eagerly without jit.save."
                ) from e
            raise
        exported_blobs.append(blob)

    meta = {
        "class_name": type(layer).__name__,
        "n_outputs": None,
        "exported": exported_blobs,
        "param_names": [k for k in state],
        "n_params": len(list(layer.named_parameters())),
    }
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f, protocol=4)


class TranslatedLayer:
    """Loaded deployable program (reference: TranslatedLayer in
    jit/translated_layer.py)."""

    def __init__(self, state, meta):
        self._state = {k: jnp.asarray(v) for k, v in state.items()}
        self._meta = meta
        self._exported = None
        if meta.get("exported"):
            from jax import export as jexport

            self._exported = jexport.deserialize(meta["exported"][0])
        self.training = False

    def __call__(self, *args):
        if self._exported is None:
            raise RuntimeError(
                "this artifact was saved without input_spec; only "
                "state_dict() is available")
        arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        # param order recorded at save time
        names = self._meta["param_names"]
        # split params vs buffers is implicit in saved call signature:
        # we re-pass all state in recorded order
        p_arrays = [self._state[k] for k in names]
        # exported signature: (params, buffers, *args) — buffers are the
        # tail of state; reconstruct by arity
        n_total = len(p_arrays)
        out = self._exported.call(p_arrays[: self._n_params],
                                  p_arrays[self._n_params: n_total], *arrs)
        outs = tuple(Tensor(o) for o in out)
        return outs[0] if len(outs) == 1 else outs

    @property
    def _n_params(self):
        return self._meta.get("n_params", len(self._meta["param_names"]))

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._state.items()}

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    return TranslatedLayer(state, meta)
