"""Graph-break diagnostics for traced programs.

TPU-native counterpart of the reference's dy2static error layer
(reference: the SOT opcode executor falls back per-opcode,
paddle/fluid/pybind/eval_frame.c:411; dy2static/error.py rewrites trace
errors with user-frame context). This framework traces under jax.jit
instead of rewriting bytecode, so a data-dependent Python branch
surfaces as a JAX concretization error mid-trace; these helpers catch
that and re-raise a framework-level GraphBreakError that names the
traced function, pinpoints the user frame, and prescribes the fix
(paddle.static.nn.cond/while_loop or an eager-only op's masked
alternative).
"""
from __future__ import annotations

import traceback

import jax

__all__ = ["GraphBreakError", "reraise_graph_break"]

# ops documented eager-only (data-dependent output shapes —
# ops/manipulation.py:6); named in the diagnostic when they appear in
# the failing trace
_EAGER_ONLY = ("nonzero", "masked_select", "unique")

_CONCRETIZATION_ERRORS = (
    jax.errors.TracerBoolConversionError,
    jax.errors.ConcretizationTypeError,
    jax.errors.NonConcreteBooleanIndexError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.TracerArrayConversionError,
)


class GraphBreakError(RuntimeError):
    """Data-dependent Python control flow (or an eager-only op) inside a
    traced program."""


def _user_frame(exc) -> str:
    """Best-effort: the deepest traceback frame outside jax/paddle_tpu
    internals (the user's `if tensor:` line)."""
    frames = traceback.extract_tb(exc.__traceback__)
    for fr in reversed(frames):
        f = fr.filename
        if "/jax/" not in f and "/paddle_tpu/" not in f:
            return f"{fr.filename}:{fr.lineno} ({fr.line})"
    return "<unknown frame>"


def reraise_graph_break(fn_name: str, exc: BaseException):
    """If ``exc`` is a JAX concretization error, raise the framework
    GraphBreakError naming the offender and the fix; otherwise return
    False so the caller re-raises the original."""
    if not isinstance(exc, _CONCRETIZATION_ERRORS):
        return False
    msg = str(exc)
    culprit = _user_frame(exc)
    hints = [
        f"graph break while tracing `{fn_name}`: the Python code makes "
        f"a data-dependent decision on a traced Tensor at {culprit}.",
        "Under @to_static / jit.TrainStep / jit.save the function is "
        "traced ONCE with abstract values, so `if tensor:`, "
        "`while tensor:`, `int(tensor)` or `tensor.numpy()` cannot "
        "run (SURVEY §7.0: no data-dependent Python control flow "
        "under jit).",
        "Fixes: use paddle.static.nn.cond(pred, true_fn, false_fn) / "
        "paddle.static.nn.while_loop for control flow; "
        "paddle.where/masking for data-dependent selection; or "
        "move the branch out of the traced function.",
    ]
    eager_ops = [op for op in _EAGER_ONLY if op in msg]
    if eager_ops:
        hints.append(
            f"Note: `{eager_ops[0]}` has a data-dependent output shape "
            "and is EAGER-ONLY (ops/manipulation.py); inside traced "
            "code use where/masking with a static bound instead.")
    hints.append(f"--- original JAX error ---\n{msg.splitlines()[0]}")
    raise GraphBreakError("\n".join(hints)) from exc
