"""@to_static: trace the eager program into one compiled XLA executable.

TPU-native equivalent of the reference's dy2static stack (reference:
python/paddle/jit/api.py:171 ``to_static``; ProgramTranslator
dy2static/program_translator.py:1724; PartialProgramLayer
dy2static/partial_program.py:151 running the traced program as one op).

Design (SURVEY.md §7.0 "functional core, imperative shell"): instead of
AST/bytecode rewriting, the eager ops already run over jax arrays — so
"to static" = swap Layer state for traced arrays, run the SAME Python
forward once under ``jax.jit`` tracing, and cache the compiled program per
input signature (the ``_ExecutorCache`` equivalent). Mutated buffers
(BN running stats) become explicit program outputs. Backward through a
compiled forward is a single tape GradNode whose vjp is a second cached
compiled program that rematerialises the forward (jax.vjp inside jit) —
remat keeps memory flat, XLA fuses fwd+bwd.

Randomness: the program takes a PRNG key operand; in-trace draws are
``fold_in(key, counter)`` (core/generator.use_trace_key), so each call gets
fresh dropout masks without recompilation.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import engine
from ..core.generator import default_generator, use_trace_key
from ..core.tensor import Parameter, Tensor

__all__ = ["StaticFunction", "to_static", "not_to_static"]


class _TensorIndex:
    """Placeholder marking a Tensor leaf's position in an output pytree."""

    __slots__ = ("i",)

    def __init__(self, i):
        self.i = i

    def __repr__(self):
        # stable repr — participates in the program-cache signature
        return f"T{self.i}"


def _flatten_tensors(obj, out: List[Tensor]):
    if isinstance(obj, Tensor):
        out.append(obj)
        return _TensorIndex(len(out) - 1)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_flatten_tensors(v, out) for v in obj)
    if isinstance(obj, dict):
        return {k: _flatten_tensors(v, out) for k, v in obj.items()}
    return obj


def _unflatten_tensors(tmpl, tensors):
    if isinstance(tmpl, _TensorIndex):
        return tensors[tmpl.i]
    if isinstance(tmpl, (list, tuple)):
        return type(tmpl)(_unflatten_tensors(v, tensors) for v in tmpl)
    if isinstance(tmpl, dict):
        return {k: _unflatten_tensors(v, tensors) for k, v in tmpl.items()}
    return tmpl


class _SwappedState:
    """Temporarily rebind a list of Tensors' buffers (trace-time)."""

    def __init__(self, tensors, arrays):
        self.tensors = tensors
        self.arrays = arrays

    def __enter__(self):
        self.saved = [t._data for t in self.tensors]
        for t, a in zip(self.tensors, self.arrays):
            t._data = a
        return self

    def __exit__(self, *exc):
        for t, s in zip(self.tensors, self.saved):
            t._data = s
        return False


class _Program:
    """One (signature → compiled fwd/bwd) entry; ≈ PartialProgramLayer."""

    def __init__(self, sf: "StaticFunction", args_tmpl, kwargs_tmpl,
                 n_args: int):
        self.sf = sf
        self.args_tmpl = args_tmpl
        self.kwargs_tmpl = kwargs_tmpl
        self.n_args = n_args
        self.out_tmpl = None
        # forward dispatches through the explicit-AOT wrapper: the same
        # single compilation jit would do, but the executable's XLA cost
        # model (flops, bytes accessed) is captured into compile.* /
        # roofline.* telemetry (profiler/roofline.py). The backward has
        # static_argnums (value-bearing), which the wrapper's
        # value-blind signature cannot key — it stays plain jit.
        from ..profiler import roofline as _roofline

        self._fwd = _roofline.AotProgram(
            f"to_static[{sf._name}]", jax.jit(self._pure_fwd))
        self._bwd = jax.jit(self._pure_bwd, static_argnums=4)

    # ---- the pure functions (traced by jax.jit) ----
    def _run_python(self, param_arrays, buffer_arrays, arg_arrays, key):
        sf = self.sf
        arg_tensors = [Tensor(a) for a in arg_arrays]
        args = _unflatten_tensors(self.args_tmpl, arg_tensors)
        kwargs = _unflatten_tensors(self.kwargs_tmpl, arg_tensors)
        with _SwappedState(sf._params + sf._buffers,
                           list(param_arrays) + list(buffer_arrays)), \
                use_trace_key(key), engine.no_grad():
            out = sf._orig_fn(*args, **kwargs)
            # read mutated buffers (BN running stats) BEFORE state restore
            new_buffers = [b._data for b in sf._buffers]
        out_tensors: List[Tensor] = []
        out_tmpl = _flatten_tensors(out, out_tensors)
        return out_tmpl, [t._data for t in out_tensors], new_buffers

    def _pure_fwd(self, param_arrays, buffer_arrays, arg_arrays, key):
        out_tmpl, out_arrays, new_buffers = self._run_python(
            param_arrays, buffer_arrays, arg_arrays, key)
        self.out_tmpl = out_tmpl  # structure is trace-invariant
        return out_arrays, new_buffers

    def _pure_bwd(self, param_arrays, buffer_arrays, arg_arrays, key,
                  diff_arg_idx, cots):
        """Recompute-forward vjp wrt (params, selected args)."""
        diff_arg_idx = tuple(diff_arg_idx)

        def f(p_arrays, d_args):
            full_args = list(arg_arrays)
            for i, a in zip(diff_arg_idx, d_args):
                full_args[i] = a
            _, out_arrays, _ = self._run_python(p_arrays, buffer_arrays,
                                                full_args, key)
            return tuple(out_arrays)

        d_arg_arrays = [arg_arrays[i] for i in diff_arg_idx]
        _, vjp_fn = jax.vjp(f, list(param_arrays), d_arg_arrays)
        p_grads, a_grads = vjp_fn(tuple(cots))
        return p_grads, a_grads

    # ---- execution ----
    def run(self, arg_tensors: List[Tensor]):
        sf = self.sf
        p_arrays = [p._data for p in sf._params]
        b_arrays = [b._data for b in sf._buffers]
        a_arrays = [t._data for t in arg_tensors]
        key = default_generator().next_key()

        try:
            out_arrays, new_buffers = self._fwd(p_arrays, b_arrays,
                                                a_arrays, key)
        except Exception as e:  # graph-break diagnostics (VERDICT r3 #7)
            from .graph_break import reraise_graph_break

            if not reraise_graph_break(sf._name, e):
                raise
        for b, nb in zip(sf._buffers, new_buffers):
            if nb is not b._data:
                b._rebind(nb)

        grad_wanted = engine.is_grad_enabled() and (
            any(not p.stop_gradient for p in sf._params)
            or any(not t.stop_gradient for t in arg_tensors))

        out_tensors = [Tensor(a) for a in out_arrays]
        if grad_wanted:
            diff_params = [p for p in sf._params if not p.stop_gradient
                           and jnp.issubdtype(p._data.dtype, jnp.inexact)]
            diff_arg_idx = tuple(
                i for i, t in enumerate(arg_tensors)
                if not t.stop_gradient
                and jnp.issubdtype(t._data.dtype, jnp.inexact))
            diff_p_idx = [i for i, p in enumerate(sf._params)
                          if not p.stop_gradient
                          and jnp.issubdtype(p._data.dtype, jnp.inexact)]
            bwd = self._bwd

            def vjp_fn(cots, _p=p_arrays, _b=b_arrays, _a=a_arrays, _k=key):
                p_grads, a_grads = bwd(_p, _b, _a, _k, diff_arg_idx, cots)
                return tuple(p_grads[i] for i in diff_p_idx) + tuple(a_grads)

            edges = []
            for p in diff_params:
                edges.append(("leaf", p))
            for i in diff_arg_idx:
                t = arg_tensors[i]
                if t._grad_node is not None:
                    edges.append(("node", t._grad_node, t._out_idx))
                else:
                    edges.append(("leaf", t))
            out_avals = [(o.shape, o.dtype) for o in out_arrays]
            node = engine.GradNode(f"to_static[{sf._name}]", vjp_fn, edges,
                                   out_avals)
            for idx, t in enumerate(out_tensors):
                if jnp.issubdtype(t._data.dtype, jnp.inexact):
                    t.stop_gradient = False
                    t._grad_node = node
                    t._out_idx = idx
        return _unflatten_tensors(self.out_tmpl, out_tensors)


class StaticFunction:
    """≈ dy2static StaticFunction (program_translator.py:324)."""

    def __init__(self, function: Callable, layer=None, input_spec=None,
                 build_strategy=None, backend=None, full_graph=True):
        self._orig_fn = function
        self._layer = layer if layer is not None else getattr(
            function, "__self__", None)
        self._input_spec = input_spec
        self._name = getattr(function, "__name__", "fn")
        self._programs: Dict[Any, _Program] = {}
        self._enabled = True
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__"))

    # state snapshot (ordered, stable across calls)
    @property
    def _params(self) -> List[Parameter]:
        if self._layer is None:
            return []
        return [p for _, p in self._layer.named_parameters()]

    @property
    def _buffers(self) -> List[Tensor]:
        if self._layer is None:
            return []
        return [b for _, b in self._layer.named_buffers()]

    def _signature(self, arg_tensors, args_tmpl, kwargs_tmpl):
        avals = tuple((tuple(t._data.shape), str(t._data.dtype),
                       bool(t.stop_gradient)) for t in arg_tensors)
        training = self._layer.training if self._layer is not None else None
        static_repr = repr((args_tmpl, kwargs_tmpl))
        n_state = (len(self._params), len(self._buffers))
        return (avals, training, static_repr, n_state,
                engine.is_grad_enabled())

    def __call__(self, *args, **kwargs):
        if not self._enabled:
            return self._orig_fn(*args, **kwargs)
        arg_tensors: List[Tensor] = []
        args_tmpl = _flatten_tensors(list(args), arg_tensors)
        kwargs_tmpl = _flatten_tensors(dict(kwargs), arg_tensors)
        sig = self._signature(arg_tensors, args_tmpl, kwargs_tmpl)
        prog = self._programs.get(sig)
        # compile telemetry: a miss means a NEW traced program for this
        # signature (a growing jit.trace count across steps with stable
        # shapes = retrace storm; steady jit.cache_hit = healthy)
        from ..profiler import stats as _stats

        if prog is None:
            _stats.inc("jit.trace")
            with _stats.timed("compile.jit_build_us"):
                prog = _Program(self, args_tmpl, kwargs_tmpl,
                                len(arg_tensors))
            self._programs[sig] = prog
        else:
            _stats.inc("jit.cache_hit")
        return prog.run(arg_tensors)

    # paddle API surface
    @property
    def program_cache(self):
        return self._programs

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    def rollback(self):
        """Restore the original eager function (paddle API)."""
        self._enabled = False
        if self._layer is not None:
            self._layer.forward = self._orig_fn
        return self._orig_fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """``paddle.jit.to_static`` (reference jit/api.py:171).

    Accepts a plain function, a Layer method, or a Layer instance (wraps its
    ``forward``); usable as decorator or call.
    """
    from ..nn import Layer

    def wrap(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, layer=layer,
                                input_spec=input_spec,
                                build_strategy=build_strategy)
            layer.forward = sf
            return layer
        if isinstance(fn, StaticFunction):
            return fn
        return StaticFunction(fn, input_spec=input_spec,
                              build_strategy=build_strategy)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    fn._not_to_static = True
    return fn
