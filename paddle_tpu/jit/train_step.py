"""Whole-step compilation: forward + backward + optimizer in ONE XLA program.

This is the TPU-idiomatic performance path (SURVEY.md §7.1 step 5 "whole
step compile (fwd+bwd+opt)"). The reference runs a step as thousands of
individually-launched kernels coordinated by the interpreter
(new_executor/program_interpreter.cc); on TPU the entire step compiles to
a single executable — XLA fuses elementwise chains into the matmuls, the
optimizer update aliases parameter buffers in HBM (donation), and the only
per-step host work is pushing the batch and pulling the scalar loss.

Used by hapi.Model.fit, bench.py, and the distributed data-parallel step
(where the same pure function is pjit'd over a mesh).
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ..core import engine
from ..core.generator import default_generator, use_trace_key
from ..core.tensor import Tensor
from .static_function import _SwappedState, _flatten_tensors

__all__ = ["TrainStep"]


class TrainStep:
    """Compile ``loss = loss_fn(model(*inputs), *labels)`` + optimizer step.

    ``step(inputs, labels)`` returns the loss Tensor; parameters, optimizer
    state and buffers are updated in place (rebound to the donated outputs).
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 in_sharding=None, donate: bool = True,
                 amp_level: Optional[str] = None,
                 amp_dtype: str = "bfloat16"):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # autocast applied around the traced forward+loss: O1 = per-op
        # white/black lists; O2 = cast-everything-except-blacklist (the
        # decorate() param cast alone is not enough — fp32 activations
        # would re-promote bf16 params at every op)
        self._amp_level = amp_level if amp_level in ("O1", "O2") else None
        self._amp_dtype = amp_dtype
        self._params = [p for _, p in model.named_parameters()]
        self._buffers = [b for _, b in model.named_buffers()]
        self._trainable_idx = [i for i, p in enumerate(self._params)
                               if not p.stop_gradient]
        donate_args = (0, 1) if donate else ()
        # explicit-AOT dispatch (profiler/roofline.py): the whole-step
        # executable's XLA cost model (flops, bytes accessed) lands in
        # compile.{flops,bytes} at compile time, so bench.py and
        # tools/*_profile.py derive MFU / bandwidth utilization from the
        # compiler's own accounting via self.roofline() instead of a
        # hand-derived flops-per-token formula
        from ..profiler import roofline as _roofline
        from ..profiler import stats as _stats

        self._program_name = f"TrainStep[{type(model).__name__}]"
        self._compiled = _roofline.AotProgram(
            self._program_name, jax.jit(self._pure_step,
                                        donate_argnums=donate_args))
        _stats.inc("jit.train_step_build")

    # ---- functional grad-clip mirror of nn.ClipGradByGlobalNorm ----
    def _clip_grads(self, grads):
        clip = self.optimizer._grad_clip
        if clip is None:
            return grads
        from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, \
            ClipGradByValue

        if isinstance(clip, ClipGradByValue):
            return [jnp.clip(g, clip.min, clip.max) for g in grads]
        if isinstance(clip, ClipGradByNorm):
            out = []
            for g in grads:
                n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                s = jnp.minimum(clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
                out.append((g * s).astype(g.dtype))
            return out
        if isinstance(clip, ClipGradByGlobalNorm):
            gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in grads)
            gnorm = jnp.sqrt(gsq)
            s = jnp.minimum(clip.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
            return [(g * s).astype(g.dtype) for g in grads]
        raise NotImplementedError(f"grad clip {type(clip)} in TrainStep")

    def _pure_step(self, param_arrays, opt_states, buffer_arrays,
                   input_arrays, label_arrays, key, hyper, per_param):
        model, loss_fn = self.model, self.loss_fn
        params, buffers = self._params, self._buffers
        t_idx = self._trainable_idx

        def loss_of(trainable_arrays):
            full = list(param_arrays)
            for i, a in zip(t_idx, trainable_arrays):
                full[i] = a
            from ..amp import auto_cast

            amp_ctx = auto_cast(enable=self._amp_level is not None,
                                level=self._amp_level or "O1",
                                dtype=self._amp_dtype)
            with _SwappedState(params + buffers,
                               full + list(buffer_arrays)), \
                    use_trace_key(key), engine.no_grad(), amp_ctx:
                inputs = [Tensor(a) for a in input_arrays]
                labels = [Tensor(a, stop_gradient=True)
                          for a in label_arrays]
                out = model(*inputs)
                outs = out if isinstance(out, (list, tuple)) else (out,)
                loss = loss_fn(*outs, *labels)
                # mutated buffers surfaced via has_aux (no tracer leak)
                new_bufs = [b._data for b in buffers]
            return (loss._data if isinstance(loss, Tensor) else loss,
                    new_bufs)

        trainable = [param_arrays[i] for i in t_idx]
        (loss, new_bufs), grads = jax.value_and_grad(
            loss_of, has_aux=True)(trainable)
        grads = self._shard_grads(grads)
        grads = self._apply_regularizers(trainable, grads)
        grads = self._clip_grads(grads)

        sts = [opt_states[i] for i in range(len(t_idx))]
        new_trainable, new_sts = self.optimizer._update_arrays(
            trainable, grads, sts, hyper, per_param)
        new_params = list(param_arrays)
        for i, a in zip(t_idx, new_trainable):
            new_params[i] = a
        return loss, new_params, new_sts, new_bufs

    def _shard_grads(self, grads):
        """ZeRO stage-2 (os_g): when the optimizer carries a grad-shard
        annotation (set by GroupShardedStage2/DygraphShardingOptimizerV2),
        constrain each gradient to Shard over the sharding axis — GSPMD
        then fuses the dp grad all-reduce with the shard into a
        reduce-scatter (reference: dygraph_sharding_optimizer.py:470)."""
        gs = getattr(self.optimizer, "_grad_shard", None)
        if gs is None:
            return grads
        mesh, axis = gs
        from ..distributed.fleet.meta_parallel.sharding.sharding_optimizer \
            import _axis_sharding, _find_shard_dim

        degree = mesh.get_dim_size(axis)
        out = []
        for g in grads:
            d = _find_shard_dim(g.shape, degree)
            if d is None:
                out.append(g)
            else:
                out.append(jax.lax.with_sharding_constraint(
                    g, _axis_sharding(mesh, axis, g.ndim, dim=d)))
        return out

    def _apply_regularizers(self, p_arrays, grads):
        opt = self.optimizer
        from ..regularizer import WeightDecayRegularizer

        wd = opt._weight_decay
        if wd is None or opt._decoupled_wd():
            regs = [self._params[i].regularizer for i in self._trainable_idx]
            if not any(regs):
                return grads
            return [r(p, g) if r is not None else g
                    for r, p, g in zip(regs, p_arrays, grads)]
        if isinstance(wd, WeightDecayRegularizer):
            return [wd(p, g) for p, g in zip(p_arrays, grads)]
        return grads

    def _build_args(self, inputs, labels):
        """Assemble the positional args of ``_pure_step`` exactly as
        ``__call__`` passes them (single source for call + lowering)."""
        opt = self.optimizer
        trainable = [self._params[i] for i in self._trainable_idx]
        fun = getattr(opt, "_apply_decay_param_fun", None)
        if fun is not None:
            opt._no_decay_ids = {id(p) for p in trainable if not fun(p.name)}
        opt_states = [opt._state_for(p) for p in trainable]
        hyper = opt._hyper()
        per_param = [opt._per_param_hyper(p) for p in trainable]
        key = default_generator().next_key()

        p_arrays = [p._data for p in self._params]
        b_arrays = [b._data for b in self._buffers]
        in_arrays = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in inputs]
        lb_arrays = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in labels]
        return (p_arrays, opt_states, b_arrays, in_arrays, lb_arrays, key,
                hyper, per_param)

    def lower_hlo(self, inputs, labels=()) -> str:
        """Lower the whole-step program for these inputs and return the
        optimized HLO text (used by HLO-assertion tests and the
        multichip dryrun; does NOT execute the step)."""
        return self._compiled.jitted \
            .lower(*self._build_args(inputs, labels)).compile().as_text()

    def roofline(self, wall_s_per_step: float):
        """Roofline for the compiled step from the XLA cost model and an
        honestly measured per-step wall time: returns a RooflineResult
        (achieved FLOP/s, achieved bytes/s, MFU, %-of-bandwidth-roofline
        vs the device peak table) and refreshes the roofline.* gauges.
        None until the step has compiled."""
        from ..profiler import roofline as _roofline

        return _roofline.analyze(self._program_name, wall_s_per_step)

    def __call__(self, inputs, labels=()):
        if isinstance(inputs, Tensor):
            inputs = [inputs]
        if isinstance(labels, Tensor):
            labels = [labels]
        opt = self.optimizer
        trainable = [self._params[i] for i in self._trainable_idx]

        # first call = trace + XLA compile (+ run): record its wall
        # seconds so bench telemetry carries cold-vs-warm compile time
        # — with FLAGS_compile_cache_dir set (persistent cache, see
        # device.setup_compile_cache) a warm process's first call drops
        # to executable-load time, and the histogram shows it
        first = not getattr(self, "_first_call_done", False)
        if first:
            import time as _time

            from ..profiler import stats as _stats

            t0 = _time.perf_counter()

        try:
            loss, new_params, new_sts, new_bufs = self._compiled(
                *self._build_args(inputs, labels))
        except Exception as e:  # graph-break diagnostics (VERDICT r3 #7)
            from .graph_break import reraise_graph_break

            if not reraise_graph_break(
                    f"TrainStep[{type(self.model).__name__}]", e):
                raise

        if first:
            self._first_call_done = True
            self.first_call_seconds = _time.perf_counter() - t0
            _stats.observe("compile.train_step_first_call_s",
                           self.first_call_seconds)
        for p, a in zip(self._params, new_params):
            p._rebind(a)
        for p, st in zip(trainable, new_sts):
            opt._accumulators[id(p)] = st
        for b, a in zip(self._buffers, new_bufs):
            b._rebind(a)
        opt._global_step += 1
        return Tensor(loss)
