"""paddle.linalg — decomposition/solver namespace.

TPU-native equivalent of the reference's linalg surface (reference:
python/paddle/linalg.py re-exporting tensor/linalg.py — svd, qr, eig,
eigh, inv, det, slogdet, cholesky, solve, lstsq, pinv, matrix_power,
triangular_solve, matrix_rank, cond, multi_dot, norm; PHI kernels
paddle/phi/kernels/*_kernel.h per op). Lowered via jnp.linalg — on TPU
the decompositions run XLA's blocked algorithms; grads come from
jax.vjp like every other dispatched op.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops.dispatch import as_tensor_args, eager_apply

__all__ = [
    "svd", "qr", "eig", "eigh", "eigvals", "eigvalsh", "inv", "det",
    "slogdet", "cholesky", "solve", "lstsq", "pinv", "matrix_power",
    "triangular_solve", "matrix_rank", "cond", "multi_dot", "norm",
    "matmul", "cross", "dot",
]


def _op(name, raw, tensors, n_outputs=1):
    return eager_apply(name, raw, as_tensor_args(*tensors),
                       n_outputs=n_outputs)


def svd(x, full_matrices=False, name=None):
    return _op("svd",
               lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
               [x], n_outputs=3)


def qr(x, mode="reduced", name=None):
    return _op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), [x],
               n_outputs=2)


def eig(x, name=None):
    return _op("eig", lambda a: tuple(jnp.linalg.eig(a)), [x], n_outputs=2)


def eigh(x, UPLO="L", name=None):
    def raw(a):
        herm = _from_triangle(a, UPLO)
        return tuple(jnp.linalg.eigh(herm, symmetrize_input=False))

    return _op("eigh", raw, [x], n_outputs=2)


def _from_triangle(a, UPLO):
    """Build the Hermitian matrix from ONE triangle (Paddle/LAPACK UPLO
    semantics — the other triangle's contents are ignored)."""
    if UPLO == "U":
        u = jnp.triu(a)
        return u + jnp.swapaxes(u, -1, -2) \
            - jnp.triu(jnp.tril(a))  # subtract diag counted twice
    low = jnp.tril(a)
    return low + jnp.swapaxes(low, -1, -2) - jnp.triu(jnp.tril(a))


def eigvals(x, name=None):
    return _op("eigvals", lambda a: jnp.linalg.eigvals(a), [x])


def eigvalsh(x, UPLO="L", name=None):
    return _op("eigvalsh",
               lambda a: jnp.linalg.eigvalsh(_from_triangle(a, UPLO)),
               [x])


def inv(x, name=None):
    return _op("inv", lambda a: jnp.linalg.inv(a), [x])


def det(x, name=None):
    return _op("det", lambda a: jnp.linalg.det(a), [x])


def slogdet(x, name=None):
    return _op("slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), [x],
               n_outputs=2)


def cholesky(x, upper=False, name=None):
    def raw(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return _op("cholesky", raw, [x])


def solve(x, y, name=None):
    return _op("solve", lambda a, b: jnp.linalg.solve(a, b), [x, y])


def lstsq(x, y, rcond=None, driver=None, name=None):
    def raw(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv

    return _op("lstsq", raw, [x, y], n_outputs=4)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _op("pinv", lambda a: jnp.linalg.pinv(
        a, rtol=rcond, hermitian=hermitian), [x])


def matrix_power(x, n, name=None):
    return _op("matrix_power",
               lambda a: jnp.linalg.matrix_power(a, n), [x])


def triangular_solve(x, y, upper=True, transpose=False,
                     unitriangular=False, name=None):
    from jax.scipy.linalg import solve_triangular

    def raw(a, b):
        return solve_triangular(a, b, lower=not upper,
                                trans=1 if transpose else 0,
                                unit_diagonal=unitriangular)

    return _op("triangular_solve", raw, [x, y])


def matrix_rank(x, tol=None, hermitian=False, name=None):
    def raw(a):
        if tol is None:
            return jnp.linalg.matrix_rank(a)
        # Paddle's tol is an ABSOLUTE singular-value threshold
        s = jnp.linalg.eigvalsh(a) if hermitian else \
            jnp.linalg.svd(a, compute_uv=False)
        return jnp.sum(jnp.abs(s) > tol, axis=-1)

    return _op("matrix_rank", raw, [x])


def cond(x, p=None, name=None):
    return _op("cond", lambda a: jnp.linalg.cond(a, p=p), [x])


def multi_dot(xs, name=None):
    return _op("multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs),
               list(xs))


def norm(x, p=None, axis=None, keepdim=False, name=None):
    from .ops import linalg as _ops_linalg

    return _ops_linalg.norm(x, p=p if p is not None else "fro",
                            axis=axis, keepdim=keepdim)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    from .ops import linalg as _ops_linalg

    return _ops_linalg.matmul(x, y, transpose_x, transpose_y)


def cross(x, y, axis=9, name=None):
    from .ops import linalg as _ops_linalg

    return _ops_linalg.cross(x, y, axis=axis)


def dot(x, y, name=None):
    from .ops import linalg as _ops_linalg

    return _ops_linalg.dot(x, y)
