"""paddle.linalg — decomposition/solver namespace.

TPU-native equivalent of the reference's linalg namespace (reference:
python/paddle/linalg.py, which re-exports tensor/linalg.py ops). One
implementation lives in ``ops/linalg.py`` (registered ops with tape
gradients); this module is the namespaced view, exactly like the
reference — no second copies to diverge.
"""
from __future__ import annotations

from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, cross, det, dot, eig,
    eigh, eigvals, eigvalsh, lstsq, lu, matmul, matrix_power, matrix_rank,
    multi_dot, norm, pinv, qr, slogdet, solve, svd, triangular_solve,
)
from .ops.linalg import inverse  # noqa: F401
from .ops.linalg import inverse as inv  # noqa: F401  (paddle.linalg.inv)

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "cross",
    "det", "dot", "eig", "eigh", "eigvals", "eigvalsh", "inv", "inverse",
    "lstsq", "lu", "matmul", "matrix_power", "matrix_rank", "multi_dot",
    "norm", "pinv", "qr", "slogdet", "solve", "svd", "triangular_solve",
]
