"""paddle_tpu.metric (reference: python/paddle/metric/metrics.py —
Metric base, Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _to_np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _to_np(pred)
        label = _to_np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label[..., 0]
        correct = (idx == label[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        correct = _to_np(correct)
        accs = []
        # samples = all dims except the trailing top-k dim
        num = int(np.prod(correct.shape[:-1])) if correct.ndim else 1
        for k in self.topk:
            c = correct[..., :k].sum()
            self.total[self.topk.index(k)] += c
            self.count[self.topk.index(k)] += num
            accs.append(c / max(num, 1))
        return np.array(accs[0] if len(accs) == 1 else accs)

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).ravel()
        labels = _to_np(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).ravel()
        labels = _to_np(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).ravel()
        if preds.ndim == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.ravel()
        bins = (pos_prob * self._num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self._num_thresholds)
        pos_mask = labels.astype(bool)
        np.add.at(self._stat_pos, bins[pos_mask], 1)
        np.add.at(self._stat_neg, bins[~pos_mask], 1)

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            auc += self._stat_neg[i] * (tot_pos + self._stat_pos[i] / 2.0)
            tot_pos += self._stat_pos[i]
            tot_neg += self._stat_neg[i]
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (paddle.metric.accuracy)."""
    pred = _to_np(input)
    lab = _to_np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    acc = float(np.mean(np.any(idx == lab[..., None], axis=-1)))
    return Tensor(np.asarray(acc, np.float32))
