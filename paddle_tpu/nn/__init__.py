"""paddle_tpu.nn — neural network layers.

Mirrors the reference's python/paddle/nn package surface.
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer_base import (  # noqa: F401
    Layer, LayerDict, LayerList, ParamAttr, ParameterList, Sequential,
)
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
)
from .layers.activation import *  # noqa: F401,F403
from .layers.common import *  # noqa: F401,F403
from .layers.conv import *  # noqa: F401,F403
from .layers.loss import *  # noqa: F401,F403
from .layers.norm import *  # noqa: F401,F403
from .layers.pooling import *  # noqa: F401,F403
from .layers.rnn import *  # noqa: F401,F403
from .layers.transformer import *  # noqa: F401,F403

from .layers import (  # noqa: F401
    activation, common, conv, loss, norm, pooling, rnn, transformer,
)

# `paddle.nn.layer` namespace alias (reference keeps layers under nn.layer)
from . import layers as layer  # noqa: F401

from . import utils  # noqa: F401
