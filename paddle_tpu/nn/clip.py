"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm). Operates on
(param, grad) lists inside Optimizer.step; global-norm clip computes one
fused norm over all grads (single compiled reduction on TPU).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _global_norm_sq(self, grads):
        return sum(jnp.sum(jnp.square(g._data.astype(jnp.float32)))
                   for g in grads)

    def __call__(self, params_grads):
        clippable = [(p, g) for p, g in params_grads
                     if g is not None and getattr(p, "need_clip", True)]
        if not clippable:
            return params_grads
        gsq = self._global_norm_sq([g for _, g in clippable])
        # distributed hook: TP/sharded optimizers override to allreduce the
        # squared norm across model-parallel ranks before the sqrt
        gsq = self._reduce_global_norm_sq(gsq)
        gnorm = jnp.sqrt(gsq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out

    def _reduce_global_norm_sq(self, gsq):
        return gsq


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style utility kept for parity (reference exposes
    paddle.nn.utils.clip_grad_norm_)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g._data.astype(jnp.float32)),
                                  norm_type)) for g in grads),
            1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._rebind((p.grad._data * scale).astype(p.grad._data.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._rebind(jnp.clip(p.grad._data, -clip_value, clip_value))
