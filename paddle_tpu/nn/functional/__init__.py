"""paddle_tpu.nn.functional — functional op surface.

Mirrors the reference's python/paddle/nn/functional package.
"""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403

from . import (  # noqa: F401
    activation, attention, common, conv, flash_varlen, grouped_gemm,
    lora, loss, norm, pooling,
)

# flash_attention module alias for `from paddle.nn.functional import
# flash_attention` style imports used by reference models
flash_attention_mod = attention

__all__ = (
    list(activation.__all__) + list(common.__all__) + list(conv.__all__)
    + list(pooling.__all__) + list(norm.__all__) + list(loss.__all__)
    + list(attention.__all__)
)
