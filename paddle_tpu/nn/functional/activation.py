"""Activation functionals.

TPU-native equivalent of the reference's activation ops
(reference: python/paddle/nn/functional/activation.py backed by PHI
activation kernels, paddle/phi/kernels/activation_kernel.h). Each op is a
pure jnp function dispatched through the eager tape; XLA fuses these into
neighbouring matmuls so no hand-written kernels are needed on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.dispatch import defun, eager_apply, as_tensor_args, inplace_apply
from ...ops.registry import all_ops, register_op

__all__ = [
    "relu", "relu_", "relu6", "gelu", "sigmoid", "log_sigmoid", "silu",
    "swish", "mish", "softmax", "softmax_", "log_softmax", "softplus",
    "softshrink", "hardshrink", "tanhshrink", "hardsigmoid", "hardswish",
    "hardtanh", "leaky_relu", "elu", "elu_", "celu", "selu", "prelu", "rrelu",
    "glu", "tanh", "tanh_", "maxout", "softsign", "thresholded_relu",
    "swiglu",
]


def _unary(name, raw):
    return defun(name, n_tensor_args=1)(raw)


relu = _unary("relu", lambda x: jax.nn.relu(x))
relu6 = _unary("relu6", lambda x: jnp.clip(x, 0.0, 6.0))
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid)
silu = _unary("silu", jax.nn.silu)
tanh = _unary("tanh", jnp.tanh)
softsign = _unary("softsign", jax.nn.soft_sign)
tanhshrink = _unary("tanhshrink", lambda x: x - jnp.tanh(x))
mish = _unary("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))


@defun("gelu", n_tensor_args=1)
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=bool(approximate))


@defun("swish", n_tensor_args=1)
def swish(x):
    return jax.nn.silu(x)


@defun("softmax", n_tensor_args=1)
def softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        from ...core.dtype import convert_dtype
        x = x.astype(convert_dtype(dtype).np_dtype)
    return jax.nn.softmax(x, axis=axis)


def softmax_(x, axis=-1, dtype=None):
    return inplace_apply("softmax_", softmax.raw_fn, as_tensor_args(x),
                         {"axis": axis, "dtype": dtype})


@defun("log_softmax", n_tensor_args=1)
def log_softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        from ...core.dtype import convert_dtype
        x = x.astype(convert_dtype(dtype).np_dtype)
    return jax.nn.log_softmax(x, axis=axis)


@defun("softplus", n_tensor_args=1)
def softplus(x, beta=1.0, threshold=20.0):
    scaled = x * beta
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


@defun("softshrink", n_tensor_args=1)
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@defun("hardshrink", n_tensor_args=1)
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defun("hardsigmoid", n_tensor_args=1)
def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    return jnp.clip(x * slope + offset, 0.0, 1.0)


@defun("hardswish", n_tensor_args=1)
def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@defun("hardtanh", n_tensor_args=1)
def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@defun("leaky_relu", n_tensor_args=1)
def leaky_relu(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


@defun("elu", n_tensor_args=1)
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


def elu_(x, alpha=1.0):
    return inplace_apply("elu_", elu.raw_fn, as_tensor_args(x),
                         {"alpha": alpha})


@defun("celu", n_tensor_args=1)
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha=alpha)


@defun("selu", n_tensor_args=1)
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defun("thresholded_relu", n_tensor_args=1)
def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


def prelu(x, weight, data_format="NCHW", name=None):
    def raw(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") and a.ndim > 1 else a.ndim - 1
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a >= 0, a, wb * a)

    return eager_apply("prelu", raw, as_tensor_args(x, weight))


@defun("rrelu", n_tensor_args=1)
def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True):
    # eval-mode (deterministic) slope; training mode draws handled by caller
    slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@defun("glu", n_tensor_args=1)
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@defun("swiglu", n_tensor_args=-1)
def swiglu(x, y=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


@defun("maxout", n_tensor_args=1)
def maxout(x, groups, axis=1):
    ax = axis if axis >= 0 else x.ndim + axis
    c = x.shape[ax]
    new_shape = x.shape[:ax] + (c // groups, groups) + x.shape[ax + 1:]
    return jnp.max(x.reshape(new_shape), axis=ax + 1)


def relu_(x):
    return inplace_apply("relu_", relu.raw_fn, as_tensor_args(x))


def tanh_(x):
    return inplace_apply("tanh_", tanh.raw_fn, as_tensor_args(x))


# the in-place family is registered with its donation contract so the
# registry stays the single source of truth for which ops may donate
# their target buffer on the compiled no-grad fast path; the base ops
# are registered alongside so every `inplace_of` resolves inside the
# registry (the tpu_lint donation audit's D-DANGLING rule)
for _name, _fn, _of, _base in (
        ("relu_", relu_, "relu", relu), ("tanh_", tanh_, "tanh", tanh),
        ("elu_", elu_, "elu", elu),
        ("softmax_", softmax_, "softmax", softmax)):
    if _of not in all_ops():  # tanh already registered by ops/math.py
        register_op(_of, _base, tags=("activation",))
    register_op(_name, _fn, inplace_of=_of, donates=(0,),
                tags=("activation", "inplace"))
