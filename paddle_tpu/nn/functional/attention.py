"""Attention functionals: SDPA + flash attention.

TPU-native equivalent of the reference's attention surface (reference:
python/paddle/nn/functional/flash_attention.py:146 ``flash_attention``,
``scaled_dot_product_attention``; CUDA FA2 via phi/backends/dynload/flashattn.h
and the memory-efficient cutlass kernel). Here the hot path is the Pallas
TPU flash-attention kernel (tiled online-softmax over VMEM blocks feeding
the MXU); off-TPU we fall back to XLA's fused ``jax.nn.dot_product_attention``
so the same API runs everywhere (the fake-device test precedent, SURVEY §4).

Layout: paddle convention [batch, seqlen, num_heads, head_dim].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .paged_attention import _enable_x64

from ...core.generator import next_rng_key
from ...ops.dispatch import eager_apply, as_tensor_args

__all__ = [
    "scaled_dot_product_attention", "flash_attention",
    "flash_attn_unpadded", "sdp_kernel",
]


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _fa_mod():
    from jax.experimental.pallas.ops.tpu import flash_attention as m

    return m


_FA_BLOCKS = None  # optional (block_q, block_k) override


def set_flash_block_sizes(block_q=None, block_k=None):
    """Tune the Pallas flash-attention tile sizes (the reference's
    per-arch FA2 launch-config knob). None restores the kernel default
    (128/128); larger tiles amortize VMEM loads for long seqs."""
    global _FA_BLOCKS
    if block_q is None and block_k is not None:
        raise ValueError(
            "set_flash_block_sizes: block_q is required when block_k "
            "is given (block_q=None resets to defaults)")
    _FA_BLOCKS = None if block_q is None else (int(block_q),
                                               int(block_k or block_q))


def _fa_blocks(m, b, h, sq, sk, d):
    if _FA_BLOCKS is None:
        # measured on v5e (GPT-1.3B, d128, s1024): vs the 128 default,
        # 256x256 tiles lift train MFU 0.444 -> 0.504 and 256x512
        # -> 0.527; 512-wide q tiles exhaust VMEM at d=128. At d<=64
        # tile bytes halve, and 512x512 wins again (bert-base s512:
        # MFU 0.330 -> 0.361, tools/bert_profile fa512, r5). Gate on
        # shapes where the bigger tile is safe and divides the seq.
        if d <= 64 and sq % 512 == 0 and sk % 512 == 0:
            bq = bk = 512
        elif d <= 128 and sq % 256 == 0 and sk % 256 == 0:
            bq = 256
            bk = 512 if sk % 512 == 0 else 256
        else:
            return m.BlockSizes.get_default(b, h, sq, sk, d)
    else:
        bq = min(_FA_BLOCKS[0], sq)
        bk = min(_FA_BLOCKS[1], sk)
        # the kernel requires tiles to divide the sequence; snap down
        # rather than fail trace-time with an opaque Pallas error
        while bq > 128 and sq % bq:
            bq //= 2
        while bk > 128 and sk % bk:
            bk //= 2
        if sq % bq or sk % bk:
            return m.BlockSizes.get_default(b, h, sq, sk, d)
    return m.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
        block_q_dq=bq)


# Own custom_vjp shell around the pallas kernel: both rules trace the
# kernel under enable_x64(False) — paddle_tpu turns x64 on globally (for
# int64 tensor parity) and the kernel's block index maps mix int32/int64
# under that flag. Wrapping only the primal call is not enough because
# custom-vjp fwd/bwd re-enter python during outer vjp tracing.
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal, scale):
    m = _fa_mod()
    with _enable_x64(False), \
            jax.default_matmul_precision("default"):
        return m._flash_attention(
            q, k, v, None, None, False, causal, scale,
            _fa_blocks(m, q.shape[0], q.shape[1], q.shape[2], q.shape[2], q.shape[3]), False)


def _flash_core_fwd(q, k, v, causal, scale):
    m = _fa_mod()
    with _enable_x64(False), \
            jax.default_matmul_precision("default"):
        out, res = m._flash_attention_fwd(
            q, k, v, None, None, False, causal, scale,
            _fa_blocks(m, q.shape[0], q.shape[1], q.shape[2], q.shape[2], q.shape[3]), False)
    return out, res


def _flash_core_bwd(causal, scale, res, do):
    m = _fa_mod()
    q = res[0]
    with _enable_x64(False), \
            jax.default_matmul_precision("default"):
        dq, dk, dv, _ds, _dseg = m._flash_attention_bwd(
            False, causal, scale, _fa_blocks(m, q.shape[0], q.shape[1], q.shape[2], q.shape[2], q.shape[3]), False, res, do)
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _pallas_flash(q, k, v, causal: bool, scale: float):
    """[b, s, h, d] in/out; pallas kernel wants [b, h, s, d]."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash_core(qt, kt, vt, causal, scale)
    return jnp.swapaxes(out, 1, 2)


def _xla_attention(q, k, v, bias, causal: bool, scale: float):
    return jax.nn.dot_product_attention(
        q, k, v, bias=bias, is_causal=causal, scale=scale)


def _attention_raw(q, k, v, *maybe_mask, causal=False, scale=None,
                   dropout_p=0.0, dropout_key=None):
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    bias = maybe_mask[0] if maybe_mask else None
    if bias is not None and bias.dtype == jnp.bool_:
        bias = jnp.where(bias, 0.0, jnp.finfo(q.dtype).min).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        # dropout on attention weights → fall back to explicit softmax path
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if bias is not None:
            logits = logits + (bias if bias.ndim == 4 else bias[:, None])
        if causal:
            s_q, s_k = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        w = jax.nn.softmax(logits, axis=-1)
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, w.shape)
        w = w * keep.astype(w.dtype) / (1.0 - dropout_p)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)
    if _use_pallas(head_dim, q.shape[1], k.shape[1], bias is not None):
        _record_backend("pallas_flash")
        return _pallas_flash(q, k, v, causal, scale)
    _record_backend("xla")
    return _xla_attention(q, k, v, bias, causal, scale)


def _use_pallas(head_dim: int, seq_q: int, seq_k: int,
                has_bias: bool) -> bool:
    """Gate for the Pallas flash kernel — its real constraints: lane-dim
    alignment (head_dim % 8; 64/96/128 all verified on v5e) and seq
    divisibility by the 128-wide q/k blocks. (Round-1 gate wrongly
    required head_dim % 128, so head_dim 64/96 models never hit flash.)"""
    return (_on_tpu() and not has_bias and head_dim % 8 == 0
            and seq_q % 128 == 0 and seq_k % 128 == 0)


_LAST_BACKEND = [None]


def _record_backend(name: str):
    _LAST_BACKEND[0] = name


def last_attention_backend():
    """Which backend the most recent attention dispatch picked
    ('pallas_flash' | 'xla') — observability for tests and the bench."""
    return _LAST_BACKEND[0]


@functools.lru_cache(maxsize=64)
def _sdp_jitted(causal: bool, dropout_p: float, has_mask: bool,
                has_key: bool):
    """One cached jitted attention program per static config: a FRESH
    closure per eager call would give the pallas_call primitive a new
    cache key every time — measured ~660ms of remote recompile per
    eager flash-attention call on the tunneled chip (OPBENCH r4)."""

    def fn(*arrs):
        dkey = arrs[-1] if has_key else None
        arrs = arrs[:-1] if has_key else arrs
        return _attention_raw(*arrs, causal=causal, dropout_p=dropout_p,
                              dropout_key=dkey)

    return jax.jit(fn)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    tensors = as_tensor_args(*((query, key, value, attn_mask)
                               if attn_mask is not None
                               else (query, key, value)))
    p = dropout_p if training else 0.0
    dkey = next_rng_key() if p > 0.0 else None
    raw = _sdp_jitted(bool(is_causal), float(p),
                      attn_mask is not None, dkey is not None)
    if dkey is not None:
        # the key rides as a traced ARG so fresh masks don't recompile
        orig = raw

        def raw(*arrs):
            return orig(*arrs, dkey)

    return eager_apply("scaled_dot_product_attention", raw, tensors)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """Paddle flash_attention parity (flash_attention.py:146): returns
    (out, softmax) — softmax is None unless return_softmax (debug-only in the
    reference; unsupported here as flash never materialises it)."""
    if return_softmax:
        raise NotImplementedError(
            "return_softmax materialises the attention matrix — unsupported "
            "by the flash path (reference only supports it in debug mode)")
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def _unpadded_dense_raw(q, k, v, cu_q, cu_k, *, scale, causal):
    """LEGACY dense varlen path: reconstructs the full segment mask and
    materializes [h, total_q, total_k] logits — O(T²) memory. Kept as
    the numerical reference for the block-skipping kernel (tests,
    bench) and behind FLAGS_attn_varlen_backend=dense; unusable at
    real packed batch sizes (a 16k-token pack needs a >=1 GiB
    intermediate per head)."""
    total_q, h, d = q.shape
    total_k = k.shape[0]
    pos_q = jnp.arange(total_q)
    pos_k = jnp.arange(total_k)
    seg_q = jnp.searchsorted(cu_q[1:], pos_q, side="right")
    seg_k = jnp.searchsorted(cu_k[1:], pos_k, side="right")
    mask = seg_q[:, None] == seg_k[None, :]
    logits = jnp.einsum("qhd,khd->hqk", q, k) * scale
    if causal:
        off_q = pos_q - cu_q[jnp.minimum(seg_q, cu_q.shape[0] - 1)]
        off_k = pos_k - cu_k[jnp.minimum(seg_k, cu_k.shape[0] - 1)]
        mask = mask & (off_q[:, None] >= off_k[None, :])
    logits = jnp.where(mask[None], logits, jnp.finfo(logits.dtype).min)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("hqk,khd->qhd", w, v)


def _unpadded_varlen_raw(q, k, v, cu_q, cu_k, *, scale, causal):
    """Varlen flash attention over a packed batch: the segment-aware
    block-skipping kernel family (nn/functional/flash_varlen.py).
    MODULE-LEVEL by design: a stable function identity plus cu_seqlens
    as TRACED operands is what lets the dispatch caches admit it — the
    old per-call closure baked cu_q/cu_k in as constants, so every
    distinct packing was a fresh function object that re-traced
    (the recompile storm; pinned by tests/test_flash_varlen.py)."""
    from ...core.flags import flag
    from .flash_varlen import flash_varlen_packed

    backend = flag("attn_varlen_backend")
    if backend == "dense":
        return _unpadded_dense_raw(q, k, v, cu_q, cu_k, scale=scale,
                                   causal=causal)
    return flash_varlen_packed(q, k, v, cu_q, cu_k, scale=scale,
                               causal=causal, backend=backend)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen flash attention (reference flash_attention.py:302).

    TPU-native treatment: the packed batch stays packed — a
    segment-aware block-skipping flash kernel visits only the tiles
    where seg_q ∩ seg_k ≠ ∅ (block map from cu_seqlens), with online
    softmax — memory O(T·d), work ∝ the sum of per-segment areas.
    cu_seqlens ride as traced operands so one compiled program serves
    every packing of the same shape.
    """
    tensors = as_tensor_args(query, key, value, cu_seqlens_q,
                             cu_seqlens_k)
    out = eager_apply(
        "flash_attn_unpadded", _unpadded_varlen_raw, tensors,
        static_kwargs={"scale": float(scale), "causal": bool(causal)})
    return out, None


class sdp_kernel:
    """Context selecting attention backends (paddle/torch-compat no-op:
    backend choice is automatic — pallas on TPU, XLA elsewhere)."""

    def __init__(self, enable_flash=True, enable_math=True,
                 enable_mem_efficient=True):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
