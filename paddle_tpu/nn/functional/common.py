"""Common functionals: linear, dropout, embedding, padding, resizing.

TPU-native equivalent of the reference's common functional ops
(reference: python/paddle/nn/functional/common.py, input.py — linear via
matmul_v2 kernel, dropout kernel with seeded mask, embedding lookup).
Dropout draws its key from the framework's stateful Generator (respecting
the TP RNGStatesTracker), keeping the reference's dropout-determinism
semantics across model-parallel ranks.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ...core.generator import next_rng_key
from ...core.tensor import Tensor
from ...ops.dispatch import defun, eager_apply, as_tensor_args

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "one_hot", "pad", "zeropad2d", "interpolate", "upsample",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "unfold", "fold",
    "affine_grid", "grid_sample",
    "cosine_similarity", "bilinear", "label_smooth", "class_center_sample",
]


def _keep_mask(key, keep_prob, shape):
    """Bernoulli(keep_prob) mask for dropout.

    On TPU the mask bits come from the hardware ``rng_bit_generator``
    (RBG) instead of jax's default threefry: threefry computes ~10
    u32 rounds per element on the VPU, measured at 42% of an entire
    BERT-base pretraining step (tools/bert_profile.py, r5). The
    threefry key is folded into the RBG key, so masks stay
    deterministic per Generator seed (the stream differs from the
    threefry stream — fine for dropout; the reference's dropout
    likewise only promises seed-determinism, not a specific stream).
    Off-TPU keeps the threefry path bit-for-bit unchanged.
    """
    if jax.default_backend() == "tpu":
        kd = jax.random.key_data(key).astype(jnp.uint32).reshape(-1)
        rbg = jax.random.wrap_key_data(
            jnp.concatenate([kd, kd])[:4], impl="rbg")
        bits = jax.random.bits(rbg, tuple(shape), jnp.uint32)
        thresh = np.uint32(
            min(int(float(keep_prob) * 2.0 ** 32), 2 ** 32 - 1))
        return bits < thresh
    return jax.random.bernoulli(key, keep_prob, tuple(shape))


def _linear_raw(a, w):
    return jnp.matmul(a, w)


def _linear_bias_raw(a, w, b):
    return jnp.matmul(a, w) + b


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b); W is [in, out] per paddle convention — a single MXU
    matmul with XLA-fused bias add. Module-level raw fns (not per-call
    closures) so the signature-keyed dispatch caches can admit them."""
    if bias is None:
        return eager_apply("linear", _linear_raw, as_tensor_args(x, weight))
    return eager_apply("linear", _linear_bias_raw,
                       as_tensor_args(x, weight, bias))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return eager_apply("dropout_scale", lambda a: a * (1.0 - p),
                              as_tensor_args(x))
        return x
    if p == 1.0:
        return eager_apply("dropout", lambda a: jnp.zeros_like(a),
                          as_tensor_args(x))
    key = next_rng_key()
    t = as_tensor_args(x)[0]
    shape = list(t._data.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        mask_shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    else:
        mask_shape = shape
    keep = _keep_mask(key, 1.0 - p, mask_shape)

    def raw(a):
        m = keep.astype(a.dtype)
        if mode == "upscale_in_train":
            return a * m / (1.0 - p)
        return a * m

    return eager_apply("dropout", raw, [t])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = next_rng_key()
    t = as_tensor_args(x)[0]
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(t._data.shape))
    a_coef = (1.0 - p + p * alpha_p ** 2) ** -0.5
    b_coef = -a_coef * p * alpha_p

    def raw(arr):
        m = keep
        return a_coef * jnp.where(m, arr, alpha_p) + b_coef

    return eager_apply("alpha_dropout", raw, [t])


def _embedding_raw(w, ids, padding_idx=None):
    out = jnp.take(w, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # weight first so its gradient flows (ids are integer → non-diff)
    return eager_apply("embedding", _embedding_raw, as_tensor_args(weight, x),
                       {"padding_idx": padding_idx})


@defun("one_hot", n_tensor_args=1)
def one_hot(x, num_classes):
    return jax.nn.one_hot(x.astype(jnp.int32), num_classes, dtype=jnp.float32)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    t = as_tensor_args(x)[0]
    nd = t.ndim
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    if len(pad) == 2 * nd:
        # full-form paddle order: [d0_lo, d0_hi, d1_lo, d1_hi, ...]
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial form applies to trailing spatial dims (NCHW: reversed pairs
        # like torch — paddle uses [left,right,top,bottom] for 4D)
        n_sp = len(pad) // 2
        pairs = [(0, 0)] * (nd - n_sp)
        sp = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_sp)]
        if data_format in ("NCHW", "NCL", "NCDHW"):
            pairs = [(0, 0), (0, 0)] + sp[::-1]
        else:
            pairs = [(0, 0)] + sp[::-1] + [(0, 0)]

    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    # pairs as a nested tuple + scalar statics: hashable, so padded
    # forwards are admissible to the dispatch caches
    return eager_apply("pad", _pad_raw, [t],
                       {"pairs": tuple(map(tuple, pairs)), "jmode": jmode,
                        "value": value})


def _pad_raw(a, pairs=(), jmode="constant", value=0.0):
    if jmode == "constant":
        return jnp.pad(a, pairs, mode="constant", constant_values=value)
    return jnp.pad(a, pairs, mode=jmode)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    t = as_tensor_args(x)[0]
    if data_format[-1] == "C" and len(data_format) > 2:
        raise NotImplementedError("interpolate supports NC... layouts")
    n_sp = t.ndim - 2
    in_sp = t._data.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_sp = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * n_sp))
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * n_sp
        out_sp = tuple(int(np.floor(in_sp[i] * float(sf[i]))) for i in range(n_sp))

    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "area"}[mode]

    def raw(a):
        out_shape = a.shape[:2] + out_sp
        if method == "area":
            # adaptive mean over source bins (paddle/torch 'area' semantics)
            r = a
            for i in range(n_sp):
                axis = 2 + i
                in_s, out_s = in_sp[i], out_sp[i]
                if in_s == out_s:
                    continue
                if in_s % out_s == 0:
                    k = in_s // out_s
                    new_shape = r.shape[:axis] + (out_s, k) + r.shape[axis + 1:]
                    r = jnp.mean(r.reshape(new_shape), axis=axis + 1)
                else:
                    starts = np.floor(np.arange(out_s) * in_s / out_s).astype(int)
                    ends = np.ceil((np.arange(out_s) + 1) * in_s / out_s).astype(int)
                    pieces = [
                        jnp.mean(jax.lax.slice_in_dim(r, s, e, axis=axis),
                                 axis=axis, keepdims=True)
                        for s, e in zip(starts, ends)]
                    r = jnp.concatenate(pieces, axis=axis)
            return r
        if method == "nearest":
            idxs = [
                jnp.floor(jnp.arange(out_sp[i]) * in_sp[i] / out_sp[i]).astype(jnp.int32)
                for i in range(n_sp)]
            r = a
            for i, idx in enumerate(idxs):
                r = jnp.take(r, idx, axis=2 + i)
            return r
        if align_corners:
            # jax.image has no align_corners; gather-based linear resize
            r = a
            for i in range(n_sp):
                out_s, in_s = out_sp[i], in_sp[i]
                if out_s == 1 or in_s == 1:
                    pos = jnp.zeros(out_s)
                else:
                    pos = jnp.arange(out_s) * (in_s - 1) / (out_s - 1)
                lo = jnp.floor(pos).astype(jnp.int32)
                hi = jnp.clip(lo + 1, 0, in_s - 1)
                w = (pos - lo).astype(a.dtype)
                ax = 2 + i
                shape = [1] * r.ndim
                shape[ax] = out_s
                wv = w.reshape(shape)
                r = jnp.take(r, lo, axis=ax) * (1 - wv) + jnp.take(r, hi, axis=ax) * wv
            return r
        return jax.image.resize(a, out_shape, method=method)

    return eager_apply("interpolate", raw, [t])


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


@defun("pixel_shuffle", n_tensor_args=1)
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    n, c, h, w = x.shape
    oc = c // (r * r)
    y = x.reshape(n, oc, r, r, h, w)
    y = jnp.transpose(y, (0, 1, 4, 2, 5, 3))
    return y.reshape(n, oc, h * r, w * r)


@defun("pixel_unshuffle", n_tensor_args=1)
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // r, r, w // r, r)
    y = jnp.transpose(y, (0, 1, 3, 5, 2, 4))
    return y.reshape(n, c * r * r, h // r, w // r)


@defun("channel_shuffle", n_tensor_args=1)
def channel_shuffle(x, groups, data_format="NCHW"):
    n, c, h, w = x.shape
    y = x.reshape(n, groups, c // groups, h, w)
    y = jnp.transpose(y, (0, 2, 1, 3, 4))
    return y.reshape(n, c, h, w)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _tuplize
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    p = _tuplize(paddings, 2)
    d = _tuplize(dilations, 2)

    def raw(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = []
        for i in range(k[0]):
            for j in range(k[1]):
                patch = a_p[:, :,
                            i * d[0]: i * d[0] + (oh - 1) * s[0] + 1: s[0],
                            j * d[1]: j * d[1] + (ow - 1) * s[1] + 1: s[1]]
                cols.append(patch.reshape(n, c, oh * ow))
        # [N, C*kh*kw, L] with channel-major ordering like the reference
        stacked = jnp.stack(cols, axis=2)  # [N, C, kh*kw, L]
        return stacked.reshape(n, c * k[0] * k[1], oh * ow)

    return eager_apply("unfold", raw, as_tensor_args(x))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    from .conv import _tuplize
    out_sz = _tuplize(output_sizes, 2)
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    p = _tuplize(paddings, 2)
    d = _tuplize(dilations, 2)

    def raw(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        oh = (out_sz[0] + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (out_sz[1] + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        a_r = a.reshape(n, c, k[0], k[1], oh, ow)
        h_p, w_p = out_sz[0] + 2 * p[0], out_sz[1] + 2 * p[1]
        out = jnp.zeros((n, c, h_p, w_p), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :,
                             i * d[0]: i * d[0] + (oh - 1) * s[0] + 1: s[0],
                             j * d[1]: j * d[1] + (ow - 1) * s[1] + 1: s[1]
                             ].add(a_r[:, :, i, j])
        return out[:, :, p[0]: p[0] + out_sz[0], p[1]: p[1] + out_sz[1]]

    return eager_apply("fold", raw, as_tensor_args(x))


def _cosine_similarity_raw(a, b, axis=1, eps=1e-8):
    dot = jnp.sum(a * b, axis=axis)
    na = jnp.sqrt(jnp.sum(a * a, axis=axis))
    nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
    return dot / jnp.maximum(na * nb, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return eager_apply("cosine_similarity", _cosine_similarity_raw,
                       as_tensor_args(x1, x2), {"axis": axis, "eps": eps})


def bilinear(x1, x2, weight, bias=None, name=None):
    has_b = bias is not None
    tensors = as_tensor_args(*((x1, x2, weight, bias) if has_b else (x1, x2, weight)))

    def raw(a, b, w, *mb):
        # w: [out, in1, in2]
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if mb:
            out = out + mb[0]
        return out

    return eager_apply("bilinear", raw, tensors)


@defun("label_smooth", n_tensor_args=1)
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError(
        "class_center_sample is a PLSC-specific op; not yet implemented")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """(ops.yaml affine_grid) 2-D affine sampling grid: theta [N, 2, 3],
    out_shape [N, C, H, W] -> grid [N, H, W, 2] in [-1, 1] coords."""
    from ...ops.dispatch import as_tensor_args

    (th,) = as_tensor_args(theta)

    def raw(t):
        N = t.shape[0]
        H, W = int(out_shape[2]), int(out_shape[3])
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gx, gy = jnp.meshgrid(xs, ys)          # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], -1)   # [H, W, 3]
        return jnp.einsum("hwk,njk->nhwj", base, t)

    return eager_apply("affine_grid", raw, [th])


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """(ops.yaml grid_sample) Sample NCHW ``x`` at ``grid`` [N, H, W, 2]
    normalized coords. bilinear/nearest; zeros/border/reflection padding."""
    from ...ops.dispatch import as_tensor_args

    ts = as_tensor_args(x, grid)

    def _unnormalize(c, size):
        if align_corners:
            return (c + 1) / 2 * (size - 1)
        return ((c + 1) * size - 1) / 2

    def _pad_index(idx, size):
        if padding_mode == "border":
            return jnp.clip(idx, 0, size - 1), None
        if padding_mode == "reflection":
            if align_corners:
                span = 2 * (size - 1)
                m = jnp.mod(jnp.abs(idx), span) if size > 1 else idx * 0
                return jnp.where(m > size - 1, span - m, m), None
            span = 2 * size
            m = jnp.mod(jnp.abs(idx + 0.5), span)
            m = jnp.where(m > size, span - m, m) - 0.5
            return jnp.clip(m, 0, size - 1), None
        valid = (idx >= 0) & (idx <= size - 1)
        return jnp.clip(idx, 0, size - 1), valid

    def raw(img, g):
        N, C, H, W = img.shape
        gx = _unnormalize(g[..., 0], W)
        gy = _unnormalize(g[..., 1], H)

        def gather(iy, ix, valid):
            b = jnp.arange(N)[:, None, None]
            v = img[b, :, iy.astype(jnp.int32), ix.astype(jnp.int32)]
            # -> [N, Hg, Wg, C]
            if valid is not None:
                v = jnp.where(valid[..., None], v, 0.0)
            return v

        if mode == "nearest":
            ix, vx = _pad_index(jnp.round(gx), W)
            iy, vy = _pad_index(jnp.round(gy), H)
            valid = None if vx is None else (vx & vy)
            out = gather(iy, ix, valid)
            return jnp.moveaxis(out, -1, 1)

        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = gx - x0
        wy = gy - y0
        out = 0.0
        for dy, wyy in ((0, 1 - wy), (1, wy)):
            for dx, wxx in ((0, 1 - wx), (1, wx)):
                ix, vx = _pad_index(x0 + dx, W)
                iy, vy = _pad_index(y0 + dy, H)
                valid = None if vx is None else (vx & vy)
                out = out + gather(iy, ix, valid) * (wxx * wyy)[..., None]
        return jnp.moveaxis(out, -1, 1)

    return eager_apply("grid_sample", raw, ts)
