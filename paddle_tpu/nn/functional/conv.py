"""Convolution functionals.

TPU-native equivalent of the reference's conv ops (reference:
python/paddle/nn/functional/conv.py → phi/kernels/conv_kernel.h, gpudnn
impls). Built on ``jax.lax.conv_general_dilated`` which XLA maps straight
onto the MXU; NCHW semantics are kept for API parity and XLA handles the
layout assignment for TPU (internally NHWC).
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ...ops.dispatch import eager_apply, as_tensor_args

__all__ = [
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
]


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(v) * n
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, n):
    """Paddle padding: int, list[int] (per-dim), list of pairs, or SAME/VALID."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # may include batch/channel dims (NCHW full-form) — strip them
        if len(padding) == n + 2:
            padding = padding[2:]
        return [tuple(p) for p in padding]
    raise ValueError(f"bad padding {padding!r}")


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_nd(n, x, weight, bias, stride, padding, dilation, groups, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    pad = _padding(padding, n)
    lhs_dn, rhs_dn, out_dn = _dim_numbers(n, channel_last)

    def raw(a, w, *maybe_bias):
        # weight layout is paddle's [out_c, in_c/groups, *k]; transpose for
        # channel-last rhs spec
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w_t = jnp.transpose(w, perm)
        else:
            w_t = w
        out = lax.conv_general_dilated(
            a, w_t, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=(lhs_dn, rhs_dn, out_dn),
            preferred_element_type=None)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.size
            out = out + b.reshape(shape)
        return out

    tensors = as_tensor_args(*( (x, weight, bias) if bias is not None else (x, weight) ))
    return eager_apply(f"conv{n}d", raw, tensors)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_nd(1, x, weight, bias, stride, padding, dilation, groups, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(2, x, weight, bias, stride, padding, dilation, groups,
                    data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(3, x, weight, bias, stride, padding, dilation, groups,
                    data_format)


def _conv_transpose_nd(n, x, weight, bias, stride, padding, output_padding,
                       dilation, groups, data_format, output_size=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    out_padding = _tuplize(output_padding, n)
    pad = _padding(padding, n)
    if isinstance(pad, str):
        pad_pairs = [(0, 0)] * n if pad == "VALID" else None
    else:
        pad_pairs = pad
    lhs_dn, rhs_dn, out_dn = _dim_numbers(n, channel_last)

    def raw(a, w, *maybe_bias):
        # paddle conv_transpose weight layout: [in_c, out_c/groups, *k]
        k = w.shape[2:]
        if pad_pairs is None:  # SAME
            tp = "SAME"
        else:
            # standard transpose-conv padding transform:
            # lhs_dilation=stride, pad_lo = dil*(k-1) - pad_lo
            tp = [
                (dilation[i] * (k[i] - 1) - pad_pairs[i][0],
                 dilation[i] * (k[i] - 1) - pad_pairs[i][1] + out_padding[i])
                for i in range(n)
            ]
        if groups > 1:
            # grouped transpose: [in_c, oc/g, *k] -> [oc, ic/g, *k] blockwise
            ic = w.shape[0]
            ocg = w.shape[1]
            wg = w.reshape((groups, ic // groups, ocg) + k)
            wg = jnp.flip(wg, axis=tuple(range(3, 3 + n)))
            wg = jnp.swapaxes(wg, 1, 2)  # [g, oc/g, ic/g, *k]
            w_oihw = wg.reshape((groups * ocg, ic // groups) + k)
        else:
            w_flip = jnp.flip(w, axis=tuple(range(2, 2 + n)))
            w_oihw = jnp.swapaxes(w_flip, 0, 1)  # [out_c, in_c, *k]
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w_rhs = jnp.transpose(w_oihw, perm)
        else:
            w_rhs = w_oihw
        out = lax.conv_general_dilated(
            a, w_rhs, window_strides=(1,) * n, padding=tp,
            lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=(lhs_dn, rhs_dn, out_dn))
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.size
            out = out + b.reshape(shape)
        return out

    tensors = as_tensor_args(*((x, weight, bias) if bias is not None else (x, weight)))
    return eager_apply(f"conv{n}d_transpose", raw, tensors)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose_nd(1, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose_nd(2, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, data_format,
                              output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(3, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, data_format,
                              output_size)
