"""Segment-aware block-skipping varlen flash attention (ROADMAP item 3).

One kernel family serves the repo's three variable-length attention
customers:

- **Packed training** (``flash_attn_unpadded``): a packed batch
  ``[total_tokens, heads, d]`` whose segment boundaries are
  ``cu_seqlens`` offsets. The old path materialized a dense
  ``[h, total_q, total_k]`` mask+logits tensor — O(T²) memory, unusable
  at real packed batch sizes.
- **Chunked prefill** (``FusedMultiTransformer.prefill_chunk_raw``) and
  the speculative-verify window (``serve.verify``): a chunk of queries
  attending to the paged KV pool. The old path round-tripped a dense
  token-major ``gather_kv_pages`` copy of every cached page per chunk —
  O(S) extra HBM writes+reads per chunk per layer.

Design (the FlashAttention-2/CUTLASS case study in PAPERS.md is the
tiling/online-softmax exemplar; "LLM Inference Acceleration via
Efficient Operation Fusion" grounds fusing the segment/causal mask into
the attention kernel instead of materializing it):

- **Block map** (:func:`varlen_block_map`): packed segments are
  CONTIGUOUS in both q and k, so the k tiles a q tile must visit form
  one interval ``[kstart, kstart+klen)``. The map is computed OUTSIDE
  the kernel (a handful of O(T) integer ops) from the traced
  ``cu_seqlens`` and rides into the kernel as scalar-prefetch operands;
  the kernel's inner loop runs ``klen`` iterations — tiles where
  ``seg_q ∩ seg_k = ∅`` are never visited, so work is proportional to
  the sum of per-segment tile areas, not ``T²``.
- **Boundary-only masking**: per-tile segment aggregates (first/last
  segment id, positions) let the kernel prove a tile is INTERIOR (one
  segment, fully causal-valid) and skip the in-tile mask entirely;
  only boundary tiles compute the ``[bq, bk]`` seg/pos compare.
- **Online softmax**, fp32 running (m, l, acc) — memory is O(T·d).
- **custom_vjp backward** built the same way: a dq kernel walks the
  forward map; a dk/dv kernel walks the TRANSPOSED map (for k tile j,
  the attending q tiles are again one interval).
- **Paged variant** (:func:`paged_prefill_attention`): K/V are read IN
  PLACE from the page-major pool via block-table-indexed DMAs (the
  scalar-prefetched table drives per-page copies), so chunked prefill
  and speculative verify stop materializing the gathered pool.
- **Off-TPU**: ``backend="interpret"`` runs the SAME Pallas kernels
  through the interpreter; ``backend="xla"`` is a tiled XLA
  implementation that visits tiles in the same order with the same
  fp32 accumulation — math-identical by construction, and the default
  off-chip (serving engines jit it on CPU CI).

Layouts: packed q/k/v are ``[total, heads, head_dim]`` (paddle
``flash_attn_unpadded`` convention); the paged pool is the repo's
page-major ``[pages, n_kv, page_size, d]``.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from ...device.vmem import KERNEL_VMEM_LIMIT_BYTES
from .paged_attention import (_enable_x64, _pltpu_compiler_params,
                              _pltpu_memspace)

__all__ = [
    "varlen_block_map", "flash_varlen_packed", "paged_prefill_attention",
    "DEFAULT_BLOCK_Q", "DEFAULT_BLOCK_K",
]

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG = -1e30          # python literal: jnp scalars would be captured consts
_NEG_SAFE = -5e29     # lse clamp floor: exp(_NEG - _NEG_SAFE) underflows to 0


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _resolve_backend(backend: str) -> str:
    if backend == "auto":
        return "pallas" if _on_tpu() else "xla"
    if backend not in ("pallas", "interpret", "xla"):
        raise ValueError(
            f"flash_varlen backend={backend!r}: expected 'auto', "
            "'pallas', 'interpret' or 'xla'")
    return backend


def _cdiv(a, b):
    return -(-a // b)


# ---------------------------------------------------------------------
# Block map
# ---------------------------------------------------------------------

@dataclasses.dataclass
class BlockMap:
    """Per-tile visit intervals + segment aggregates (all int32 jnp
    arrays, computed from traced cu_seqlens — one trace serves every
    packing of the same shape).

    Forward map: q tile ``i`` visits k tiles ``kstart[i] ..
    kstart[i]+klen[i]-1``. Transposed map (the dk/dv walk): k tile
    ``j`` is visited by q tiles ``qstart2[j] .. qstart2[j]+qlen2[j]-1``.
    ``n_active = sum(klen)`` is the exact number of computed tiles —
    the skip-count tests pin it against the per-segment closed form.
    """
    kstart: jnp.ndarray   # [nq]
    klen: jnp.ndarray     # [nq]
    qslo: jnp.ndarray     # [nq] segment id of tile's first row
    qshi: jnp.ndarray     # [nq] segment id of tile's LAST row — pad
    #                       tails land in the phantom segment, so a
    #                       partially-padded tile never tests interior
    qpos0: jnp.ndarray    # [nq] in-segment position of tile's first row
    kslo: jnp.ndarray     # [nk]
    kshi: jnp.ndarray     # [nk] (same phantom-segment convention)
    kmax: jnp.ndarray     # [nk] in-segment position of tile's last row
    qstart2: jnp.ndarray  # [nk]
    qlen2: jnp.ndarray    # [nk]
    qmeta: jnp.ndarray    # [2, tq_pad] rows: (segment id, in-seg pos)
    kmeta: jnp.ndarray    # [2, tk_pad]
    n_active: jnp.ndarray  # scalar: tiles actually computed


def _seg_pos(cu, total_pad):
    """Per-token (segment id, in-segment position) for a padded packed
    axis. Tokens past ``cu[-1]`` land in the phantom segment ``nseg``
    (matched by nothing real — boundary masks kill them)."""
    pos = jnp.arange(total_pad, dtype=jnp.int32)
    seg = jnp.searchsorted(cu[1:], pos, side="right").astype(jnp.int32)
    start = cu[jnp.minimum(seg, cu.shape[0] - 1)]
    return seg, pos - start


def varlen_block_map(cu_q, cu_k, total_q_pad: int, total_k_pad: int,
                     block_q: int, block_k: int, causal: bool) -> BlockMap:
    """Build the block-skipping visit map from cu_seqlens.

    ``cu_q``/``cu_k``: int32 ``[nseg+1]`` cumulative offsets (traced or
    concrete). ``total_*_pad``: the padded (tile-aligned) axis lengths.
    """
    cu_q = jnp.asarray(cu_q, jnp.int32)
    cu_k = jnp.asarray(cu_k, jnp.int32)
    nseg = cu_q.shape[0] - 1
    nq = total_q_pad // block_q
    nk = total_k_pad // block_k
    tqr = cu_q[nseg]                      # real token counts (traced)
    tkr = cu_k[nseg]
    cu_k_ext = jnp.concatenate([cu_k, tkr[None]])   # segment nseg empty
    cu_q_ext = jnp.concatenate([cu_q, tqr[None]])

    seg_q, off_q = _seg_pos(cu_q, total_q_pad)
    seg_k, off_k = _seg_pos(cu_k, total_k_pad)

    # ---- forward map: per q tile, the contiguous k-tile interval ----
    row_lo = jnp.arange(nq, dtype=jnp.int32) * block_q
    # clamped last REAL row: drives the visit-interval arithmetic
    row_hi = jnp.clip(row_lo + block_q - 1, 0, jnp.maximum(tqr - 1, 0))
    row_hi = jnp.maximum(row_hi, row_lo)  # all-pad tiles: degenerate
    qslo = seg_q[jnp.minimum(row_lo, total_q_pad - 1)]
    qshi_c = seg_q[row_hi]
    # UNclamped last row: drives the interior test — a tile whose tail
    # is padding lands in the phantom segment and stays a boundary
    # tile (the kernel must mask its pad rows)
    qshi = seg_q[jnp.minimum(row_lo + block_q - 1, total_q_pad - 1)]
    qpos0 = off_q[jnp.minimum(row_lo, total_q_pad - 1)]
    kstart_tok = cu_k[jnp.minimum(qslo, nseg)]
    kend_tok = cu_k_ext[jnp.minimum(qshi_c, nseg) + 1]
    if causal:
        lim = cu_k[jnp.minimum(qshi_c, nseg)] \
            + (row_hi - cu_q[jnp.minimum(qshi_c, nseg)]) + 1
        kend_tok = jnp.minimum(kend_tok, jnp.maximum(lim, kstart_tok))
    kstart_tile = kstart_tok // block_k
    kend_tile = _cdiv(kend_tok, block_k)
    klen = jnp.maximum(kend_tile - kstart_tile, 0)
    klen = jnp.where(row_lo < tqr, klen, 0)
    kstart_tile = jnp.minimum(kstart_tile, jnp.maximum(nk - 1, 0))

    # ---- per-k-tile aggregates ----
    col_lo = jnp.arange(nk, dtype=jnp.int32) * block_k
    col_hi = jnp.clip(col_lo + block_k - 1, 0, jnp.maximum(tkr - 1, 0))
    col_hi = jnp.maximum(col_hi, col_lo)
    col_hi_raw = jnp.minimum(col_lo + block_k - 1, total_k_pad - 1)
    kslo = seg_k[jnp.minimum(col_lo, total_k_pad - 1)]
    kshi_c = seg_k[col_hi]
    kshi = seg_k[col_hi_raw]        # unclamped: pad tail => boundary
    kmax = off_k[col_hi_raw]

    # ---- transposed map: per k tile, the attending q-tile interval ----
    qstart_tok = cu_q[jnp.minimum(kslo, nseg)]
    if causal:
        # the earliest attending row of the tile's FIRST segment is at
        # the tile's first in-segment k position (rows before it are
        # strictly causal-masked); clamp inside the segment
        qstart_tok = jnp.minimum(
            qstart_tok + off_k[jnp.minimum(col_lo, total_k_pad - 1)],
            cu_q_ext[jnp.minimum(kslo, nseg) + 1])
    qend_tok = cu_q_ext[jnp.minimum(kshi_c, nseg) + 1]
    qstart2 = qstart_tok // block_q
    qend2 = _cdiv(qend_tok, block_q)
    qlen2 = jnp.maximum(qend2 - qstart2, 0)
    qlen2 = jnp.where(col_lo < tkr, qlen2, 0)
    qstart2 = jnp.minimum(qstart2, jnp.maximum(nq - 1, 0))

    return BlockMap(
        kstart=kstart_tile.astype(jnp.int32),
        klen=klen.astype(jnp.int32),
        qslo=qslo, qshi=qshi, qpos0=qpos0,
        kslo=kslo, kshi=kshi, kmax=kmax,
        qstart2=qstart2.astype(jnp.int32),
        qlen2=qlen2.astype(jnp.int32),
        qmeta=jnp.stack([seg_q, off_q]),
        kmeta=jnp.stack([seg_k, off_k]),
        n_active=jnp.sum(klen).astype(jnp.int32),
    )


# ---------------------------------------------------------------------
# Packed kernels (Pallas; interpret=True is the off-TPU debug path)
# ---------------------------------------------------------------------

def _boundary_mask(sq, pq, sk, pk, causal: bool):
    """[bq, bk] validity for a boundary tile from per-token metadata."""
    msk = sq[:, None] == sk[None, :]
    if causal:
        msk = jnp.logical_and(msk, pq[:, None] >= pk[None, :])
    return msk


def _packed_fwd_pallas(qt, kt, vt, bm: BlockMap, scale: float,
                       causal: bool, block_q: int, block_k: int,
                       interpret: bool):
    """Forward kernel. qt/kt/vt: [h, T_pad, d]. Returns
    (out [h, tq_pad, d] f32, lse [h, tq_pad] f32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    h, tq, d = qt.shape
    tk = kt.shape[1]
    bq, bk = block_q, block_k
    nq = tq // bq

    def kernel(kstart, klen, qslo, qshi, qpos0, kslo, kshi, kmax,
               qmeta_ref, q_ref, kmeta_hbm, k_hbm, v_hbm,
               o_ref, lse_ref, kbuf, vbuf, kmbuf, ksem, vsem, msem):
        i = pl.program_id(0)
        ks = kstart[i]
        kl = klen[i]

        def dmas(j, slot):
            return (
                pltpu.make_async_copy(
                    k_hbm.at[:, pl.ds(j * bk, bk), :], kbuf.at[slot],
                    ksem.at[slot]),
                pltpu.make_async_copy(
                    v_hbm.at[:, pl.ds(j * bk, bk), :], vbuf.at[slot],
                    vsem.at[slot]),
                pltpu.make_async_copy(
                    kmeta_hbm.at[:, pl.ds(j * bk, bk)], kmbuf.at[slot],
                    msem.at[slot]))

        @pl.when(kl > 0)
        def _():
            for c in dmas(ks, jnp.int32(0)):
                c.start()

        # fold the softmax scale into q once per tile
        # tpu-lint: ok(X-PROMOTE) -- fp32 softmax accumulator by design
        qf = q_ref[...].astype(jnp.float32) * jnp.float32(scale)
        sq = qmeta_ref[0]
        pq = qmeta_ref[1]
        uniform_q = qslo[i] == qshi[i]

        m0 = jnp.full((h, bq), _NEG, jnp.float32)
        l0 = jnp.zeros((h, bq), jnp.float32)
        a0 = jnp.zeros((h, bq, d), jnp.float32)

        def body(s, carry):
            m, l, acc = carry
            j = ks + s
            slot = jax.lax.rem(s, jnp.int32(2))

            @pl.when(s + 1 < kl)
            def _():
                for c in dmas(j + 1, jax.lax.rem(s + 1, jnp.int32(2))):
                    c.start()

            for c in dmas(j, slot):
                c.wait()
            kf = kbuf[slot].astype(jnp.float32)
            vf = vbuf[slot].astype(jnp.float32)
            lg = jax.lax.dot_general(
                qf, kf, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)      # [h, bq, bk]
            interior = jnp.logical_and(
                jnp.logical_and(uniform_q, kslo[j] == kshi[j]),
                qslo[i] == kslo[j])
            if causal:
                interior = jnp.logical_and(interior,
                                           kmax[j] <= qpos0[i])

            def _masked(z):
                msk = _boundary_mask(sq, pq, kmbuf[slot, 0],
                                     kmbuf[slot, 1], causal)
                return (jnp.where(msk[None], z, jnp.float32(_NEG)),
                        msk.astype(jnp.float32))

            def _plain(z):
                return z, jnp.ones((bq, bk), jnp.float32)

            lg, mskf = jax.lax.cond(interior, _plain, _masked, lg)
            pm = jnp.maximum(m, lg.max(-1))
            alpha = jnp.exp(m - pm)
            p = jnp.exp(lg - pm[..., None]) * mskf[None]
            l = l * alpha + p.sum(-1)
            pv = jax.lax.dot_general(
                p, vf, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)      # [h, bq, d]
            acc = acc * alpha[..., None] + pv
            return pm, l, acc

        m, l, acc = jax.lax.fori_loop(jnp.int32(0), kl, body,
                                      (m0, l0, a0))
        o_ref[...] = acc / jnp.maximum(l, jnp.float32(1e-30))[..., None]
        lse_ref[...] = jnp.where(
            l > 0, m + jnp.log(jnp.maximum(l, jnp.float32(1e-30))),
            jnp.float32(_NEG))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(nq,),
        in_specs=[
            pl.BlockSpec((2, bq), lambda i, *_: (0, i)),
            pl.BlockSpec((h, bq, d), lambda i, *_: (0, i, 0)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        out_specs=[
            pl.BlockSpec((h, bq, d), lambda i, *_: (0, i, 0)),
            pl.BlockSpec((h, bq), lambda i, *_: (0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, h, bk, d), kt.dtype),
            pltpu.VMEM((2, h, bk, d), vt.dtype),
            pltpu.VMEM((2, 2, bk), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ])
    with _enable_x64(False):
        out, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((h, tq, d), jnp.float32),
                jax.ShapeDtypeStruct((h, tq), jnp.float32),
            ],
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(bm.kstart, bm.klen, bm.qslo, bm.qshi, bm.qpos0,
          bm.kslo, bm.kshi, bm.kmax,
          bm.qmeta, qt, bm.kmeta, kt, vt)
    return out, lse


def _packed_dq_pallas(qt, kt, vt, dot_, lse, delta, bm: BlockMap,
                      scale: float, causal: bool, block_q: int,
                      block_k: int, interpret: bool):
    """dq kernel: walks the forward map again; P is recomputed from
    lse. Returns dq [h, tq_pad, d] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    h, tq, d = qt.shape
    bq, bk = block_q, block_k
    nq = tq // bq

    def kernel(kstart, klen, qslo, qshi, qpos0, kslo, kshi, kmax,
               qmeta_ref, q_ref, do_ref, ld_ref, kmeta_hbm, k_hbm,
               v_hbm, dq_ref, kbuf, vbuf, kmbuf, ksem, vsem, msem):
        i = pl.program_id(0)
        ks = kstart[i]
        kl = klen[i]

        def dmas(j, slot):
            return (
                pltpu.make_async_copy(
                    k_hbm.at[:, pl.ds(j * bk, bk), :], kbuf.at[slot],
                    ksem.at[slot]),
                pltpu.make_async_copy(
                    v_hbm.at[:, pl.ds(j * bk, bk), :], vbuf.at[slot],
                    vsem.at[slot]),
                pltpu.make_async_copy(
                    kmeta_hbm.at[:, pl.ds(j * bk, bk)], kmbuf.at[slot],
                    msem.at[slot]))

        @pl.when(kl > 0)
        def _():
            for c in dmas(ks, jnp.int32(0)):
                c.start()

        # tpu-lint: ok(X-PROMOTE) -- fp32 softmax accumulator by design
        qf = q_ref[...].astype(jnp.float32) * jnp.float32(scale)
        dof = do_ref[...].astype(jnp.float32)
        lse_t = jnp.maximum(ld_ref[0], jnp.float32(_NEG_SAFE))
        delta_t = ld_ref[1]
        sq = qmeta_ref[0]
        pq = qmeta_ref[1]
        uniform_q = qslo[i] == qshi[i]

        def body(s, dq):
            j = ks + s
            slot = jax.lax.rem(s, jnp.int32(2))

            @pl.when(s + 1 < kl)
            def _():
                for c in dmas(j + 1, jax.lax.rem(s + 1, jnp.int32(2))):
                    c.start()

            for c in dmas(j, slot):
                c.wait()
            kf = kbuf[slot].astype(jnp.float32)
            vf = vbuf[slot].astype(jnp.float32)
            lg = jax.lax.dot_general(
                qf, kf, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            interior = jnp.logical_and(
                jnp.logical_and(uniform_q, kslo[j] == kshi[j]),
                qslo[i] == kslo[j])
            if causal:
                interior = jnp.logical_and(interior,
                                           kmax[j] <= qpos0[i])

            def _masked(z):
                msk = _boundary_mask(sq, pq, kmbuf[slot, 0],
                                     kmbuf[slot, 1], causal)
                return (jnp.where(msk[None], z, jnp.float32(_NEG)),
                        msk.astype(jnp.float32))

            def _plain(z):
                return z, jnp.ones((bq, bk), jnp.float32)

            lg, mskf = jax.lax.cond(interior, _plain, _masked, lg)
            p = jnp.exp(lg - lse_t[..., None]) * mskf[None]
            dp = jax.lax.dot_general(
                dof, vf, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)      # [h, bq, bk]
            ds = p * (dp - delta_t[..., None])
            return dq + jax.lax.dot_general(
                ds, kf, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)

        dq = jax.lax.fori_loop(jnp.int32(0), kl, body,
                               jnp.zeros((h, bq, d), jnp.float32))
        dq_ref[...] = dq * jnp.float32(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(nq,),
        in_specs=[
            pl.BlockSpec((2, bq), lambda i, *_: (0, i)),
            pl.BlockSpec((h, bq, d), lambda i, *_: (0, i, 0)),
            pl.BlockSpec((h, bq, d), lambda i, *_: (0, i, 0)),
            pl.BlockSpec((2, h, bq), lambda i, *_: (0, 0, i)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        out_specs=pl.BlockSpec((h, bq, d), lambda i, *_: (0, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, h, bk, d), kt.dtype),
            pltpu.VMEM((2, h, bk, d), vt.dtype),
            pltpu.VMEM((2, 2, bk), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ])
    ld = jnp.stack([lse, delta])                         # [2, h, tq]
    with _enable_x64(False):
        dq = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((h, tq, d), jnp.float32),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(bm.kstart, bm.klen, bm.qslo, bm.qshi, bm.qpos0,
          bm.kslo, bm.kshi, bm.kmax,
          bm.qmeta, qt, dot_, ld, bm.kmeta, kt, vt)
    return dq


def _packed_dkv_pallas(qt, kt, vt, dot_, lse, delta, bm: BlockMap,
                       scale: float, causal: bool, block_q: int,
                       block_k: int, interpret: bool):
    """dk/dv kernel: walks the TRANSPOSED map — for k tile j the
    attending q tiles are the interval [qstart2[j], +qlen2[j])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    h, tq, d = qt.shape
    tk = kt.shape[1]
    bq, bk = block_q, block_k
    nk = tk // bk

    def kernel(qstart2, qlen2, qslo, qshi, qpos0, kslo, kshi, kmax,
               kmeta_ref, k_ref, v_ref, qmeta_hbm, q_hbm, do_hbm,
               ld_hbm, dk_ref, dv_ref, qbuf, dobuf, ldbuf, qmbuf,
               qsem, dosem, ldsem, qmsem):
        j = pl.program_id(0)
        qs = qstart2[j]
        ql = qlen2[j]

        def dmas(t, slot):
            return (
                pltpu.make_async_copy(
                    q_hbm.at[:, pl.ds(t * bq, bq), :], qbuf.at[slot],
                    qsem.at[slot]),
                pltpu.make_async_copy(
                    do_hbm.at[:, pl.ds(t * bq, bq), :], dobuf.at[slot],
                    dosem.at[slot]),
                pltpu.make_async_copy(
                    ld_hbm.at[:, :, pl.ds(t * bq, bq)], ldbuf.at[slot],
                    ldsem.at[slot]),
                pltpu.make_async_copy(
                    qmeta_hbm.at[:, pl.ds(t * bq, bq)], qmbuf.at[slot],
                    qmsem.at[slot]))

        @pl.when(ql > 0)
        def _():
            for c in dmas(qs, jnp.int32(0)):
                c.start()

        # tpu-lint: ok(X-PROMOTE) -- fp32 softmax accumulator by design
        kf = k_ref[...].astype(jnp.float32)
        vf = v_ref[...].astype(jnp.float32)
        sk = kmeta_ref[0]
        pk = kmeta_ref[1]
        uniform_k = kslo[j] == kshi[j]

        def body(s, carry):
            dk, dv = carry
            t = qs + s
            slot = jax.lax.rem(s, jnp.int32(2))

            @pl.when(s + 1 < ql)
            def _():
                for c in dmas(t + 1, jax.lax.rem(s + 1, jnp.int32(2))):
                    c.start()

            for c in dmas(t, slot):
                c.wait()
            qf = qbuf[slot].astype(jnp.float32) * jnp.float32(scale)
            dof = dobuf[slot].astype(jnp.float32)
            lse_t = jnp.maximum(ldbuf[slot, 0], jnp.float32(_NEG_SAFE))
            delta_t = ldbuf[slot, 1]
            lg = jax.lax.dot_general(
                qf, kf, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)      # [h, bq, bk]
            interior = jnp.logical_and(
                jnp.logical_and(uniform_k, qslo[t] == qshi[t]),
                qslo[t] == kslo[j])
            if causal:
                interior = jnp.logical_and(interior,
                                           kmax[j] <= qpos0[t])

            def _masked(z):
                msk = _boundary_mask(qmbuf[slot, 0], qmbuf[slot, 1],
                                     sk, pk, causal)
                return (jnp.where(msk[None], z, jnp.float32(_NEG)),
                        msk.astype(jnp.float32))

            def _plain(z):
                return z, jnp.ones((bq, bk), jnp.float32)

            lg, mskf = jax.lax.cond(interior, _plain, _masked, lg)
            p = jnp.exp(lg - lse_t[..., None]) * mskf[None]
            dv = dv + jax.lax.dot_general(
                p, dof, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)      # [h, bk, d]
            dp = jax.lax.dot_general(
                dof, vf, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)      # [h, bq, bk]
            ds = p * (dp - delta_t[..., None])
            dk = dk + jax.lax.dot_general(
                ds, qf, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)      # [h, bk, d]
            return dk, dv

        dk, dv = jax.lax.fori_loop(
            jnp.int32(0), ql, body,
            (jnp.zeros((h, bk, d), jnp.float32),
             jnp.zeros((h, bk, d), jnp.float32)))
        dk_ref[...] = dk        # scale already folded into qf
        dv_ref[...] = dv

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(nk,),
        in_specs=[
            pl.BlockSpec((2, bk), lambda j, *_: (0, j)),
            pl.BlockSpec((h, bk, d), lambda j, *_: (0, j, 0)),
            pl.BlockSpec((h, bk, d), lambda j, *_: (0, j, 0)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        out_specs=[
            pl.BlockSpec((h, bk, d), lambda j, *_: (0, j, 0)),
            pl.BlockSpec((h, bk, d), lambda j, *_: (0, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, h, bq, d), qt.dtype),
            pltpu.VMEM((2, h, bq, d), dot_.dtype),
            pltpu.VMEM((2, 2, h, bq), jnp.float32),
            pltpu.VMEM((2, 2, bq), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ])
    ld = jnp.stack([lse, delta])                         # [2, h, tq]
    with _enable_x64(False):
        dk, dv = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((h, tk, d), jnp.float32),
                jax.ShapeDtypeStruct((h, tk, d), jnp.float32),
            ],
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(bm.qstart2, bm.qlen2, bm.qslo, bm.qshi, bm.qpos0,
          bm.kslo, bm.kshi, bm.kmax,
          bm.kmeta, kt, vt, bm.qmeta, qt, dot_, ld)
    return dk, dv


# ---------------------------------------------------------------------
# Packed XLA fallback (math-identical tile walk, pure jax ops)
# ---------------------------------------------------------------------

def _packed_fwd_xla(qt, kt, vt, bm: BlockMap, scale: float,
                    causal: bool, block_q: int, block_k: int):
    """Same tile visit order and fp32 accumulation as the kernel, as a
    fori_loop over visit slots (slot s of q tile i is k tile
    ``kstart[i]+s``). Work is bounded by the LONGEST per-tile interval,
    memory by O(T·d) — no [T, T] intermediate ever exists."""
    h, tq, d = qt.shape
    tk = kt.shape[1]
    bq, bk = block_q, block_k
    nq, nk = tq // bq, tk // bk

    # tpu-lint: ok(X-PROMOTE) -- fp32 softmax accumulator by design
    q4 = (qt.astype(jnp.float32) * jnp.float32(scale)) \
        .reshape(h, nq, bq, d)
    k4 = kt.astype(jnp.float32).reshape(h, nk, bk, d)
    v4 = vt.astype(jnp.float32).reshape(h, nk, bk, d)
    sq4 = bm.qmeta[0].reshape(nq, bq)
    pq4 = bm.qmeta[1].reshape(nq, bq)
    sk4 = bm.kmeta[0].reshape(nk, bk)
    pk4 = bm.kmeta[1].reshape(nk, bk)
    maxlen = jnp.max(bm.klen).astype(jnp.int32)

    def body(s, carry):
        m, l, acc = carry
        j = jnp.clip(bm.kstart + s, 0, nk - 1)           # [nq]
        active = s < bm.klen                             # [nq]
        ktile = jnp.take(k4, j, axis=1)                  # [h, nq, bk, d]
        vtile = jnp.take(v4, j, axis=1)
        sk = jnp.take(sk4, j, axis=0)                    # [nq, bk]
        pk = jnp.take(pk4, j, axis=0)
        # tpu-lint: ok(X-PROMOTE) -- attention scores fp32 by design
        lg = jnp.einsum("hnqd,hnkd->hnqk", q4, ktile)    # [h,nq,bq,bk]
        msk = sq4[:, :, None] == sk[:, None, :]          # [nq, bq, bk]
        if causal:
            msk = jnp.logical_and(msk,
                                  pq4[:, :, None] >= pk[:, None, :])
        msk = jnp.logical_and(msk, active[:, None, None])
        lg = jnp.where(msk[None], lg, jnp.float32(_NEG))
        pm = jnp.maximum(m, lg.max(-1))
        alpha = jnp.exp(m - pm)
        p = jnp.exp(lg - pm[..., None]) * msk[None].astype(jnp.float32)
        l = l * alpha + p.sum(-1)
        # tpu-lint: ok(X-PROMOTE) -- fp32 PV accumulation pairs with scores
        pv = jnp.einsum("hnqk,hnkd->hnqd", p, vtile)
        acc = acc * alpha[..., None] + pv
        return pm, l, acc

    m0 = jnp.full((h, nq, bq), _NEG, jnp.float32)
    l0 = jnp.zeros((h, nq, bq), jnp.float32)
    a0 = jnp.zeros((h, nq, bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(jnp.int32(0), maxlen, body,
                                  (m0, l0, a0))
    out = (acc / jnp.maximum(l, 1e-30)[..., None]).reshape(h, tq, d)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)),
                    jnp.float32(_NEG)).reshape(h, tq)
    return out, lse


def _packed_bwd_xla(qt, kt, vt, dot_, lse, delta, bm: BlockMap,
                    scale: float, causal: bool, block_q: int,
                    block_k: int):
    """XLA backward: dq over the forward map, dk/dv over the
    transposed map — the same walks as the Pallas backward kernels."""
    h, tq, d = qt.shape
    tk = kt.shape[1]
    bq, bk = block_q, block_k
    nq, nk = tq // bq, tk // bk

    qf4 = (qt.astype(jnp.float32) * jnp.float32(scale)) \
        .reshape(h, nq, bq, d)
    do4 = dot_.astype(jnp.float32).reshape(h, nq, bq, d)
    k4 = kt.astype(jnp.float32).reshape(h, nk, bk, d)
    v4 = vt.astype(jnp.float32).reshape(h, nk, bk, d)
    lse4 = jnp.maximum(lse, jnp.float32(_NEG_SAFE)).reshape(h, nq, bq)
    dl4 = delta.reshape(h, nq, bq)
    sq4 = bm.qmeta[0].reshape(nq, bq)
    pq4 = bm.qmeta[1].reshape(nq, bq)
    sk4 = bm.kmeta[0].reshape(nk, bk)
    pk4 = bm.kmeta[1].reshape(nk, bk)

    def tile_mask(sq, pq, sk, pk, active):
        msk = sq[:, :, None] == sk[:, None, :]
        if causal:
            msk = jnp.logical_and(msk, pq[:, :, None] >= pk[:, None, :])
        return jnp.logical_and(msk, active[:, None, None])

    def dq_body(s, dq):
        j = jnp.clip(bm.kstart + s, 0, nk - 1)
        active = s < bm.klen
        ktile = jnp.take(k4, j, axis=1)
        vtile = jnp.take(v4, j, axis=1)
        msk = tile_mask(sq4, pq4, jnp.take(sk4, j, axis=0),
                        jnp.take(pk4, j, axis=0), active)
        lg = jnp.einsum("hnqd,hnkd->hnqk", qf4, ktile)
        lg = jnp.where(msk[None], lg, jnp.float32(_NEG))
        p = jnp.exp(lg - lse4[..., None]) \
            * msk[None].astype(jnp.float32)
        dp = jnp.einsum("hnqd,hnkd->hnqk", do4, vtile)
        ds = p * (dp - dl4[..., None])
        return dq + jnp.einsum("hnqk,hnkd->hnqd", ds, ktile)

    maxlen = jnp.max(bm.klen).astype(jnp.int32)
    dq = jax.lax.fori_loop(
        jnp.int32(0), maxlen, dq_body,
        jnp.zeros((h, nq, bq, d), jnp.float32))
    dq = (dq * jnp.float32(scale)).reshape(h, tq, d)

    def dkv_body(s, carry):
        dk, dv = carry
        t = jnp.clip(bm.qstart2 + s, 0, nq - 1)          # [nk]
        active = s < bm.qlen2
        qtile = jnp.take(qf4, t, axis=1)                 # [h, nk, bq, d]
        dtile = jnp.take(do4, t, axis=1)
        ltile = jnp.take(lse4, t, axis=1)                # [h, nk, bq]
        dltile = jnp.take(dl4, t, axis=1)
        sq = jnp.take(sq4, t, axis=0)                    # [nk, bq]
        pq = jnp.take(pq4, t, axis=0)
        msk = tile_mask(sq, pq, sk4, pk4, active)        # [nk, bq, bk]
        lg = jnp.einsum("hnqd,hnkd->hnqk", qtile, k4)
        lg = jnp.where(msk[None], lg, jnp.float32(_NEG))
        p = jnp.exp(lg - ltile[..., None]) \
            * msk[None].astype(jnp.float32)
        dv = dv + jnp.einsum("hnqk,hnqd->hnkd", p, dtile)
        dp = jnp.einsum("hnqd,hnkd->hnqk", dtile, v4)
        ds = p * (dp - dltile[..., None])
        dk = dk + jnp.einsum("hnqk,hnqd->hnkd", ds, qtile)
        return dk, dv

    maxlen2 = jnp.max(bm.qlen2).astype(jnp.int32)
    dk, dv = jax.lax.fori_loop(
        jnp.int32(0), maxlen2, dkv_body,
        (jnp.zeros((h, nk, bk, d), jnp.float32),
         jnp.zeros((h, nk, bk, d), jnp.float32)))
    return dq, dk.reshape(h, tk, d), dv.reshape(h, tk, d)


# ---------------------------------------------------------------------
# Packed public entry (custom_vjp)
# ---------------------------------------------------------------------

def _pad_axis(x, axis, target):
    n = x.shape[axis]
    if n == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, target - n)
    return jnp.pad(x, pads)


def _packed_prepare(q, k, v, cu_q, cu_k, causal, scale, bq, bk):
    tq, h, d = q.shape
    tk = k.shape[0]
    tqp = _cdiv(tq, bq) * bq
    tkp = _cdiv(tk, bk) * bk
    qt = _pad_axis(jnp.swapaxes(q, 0, 1), 1, tqp)        # [h, tqp, d]
    kt = _pad_axis(jnp.swapaxes(k, 0, 1), 1, tkp)
    vt = _pad_axis(jnp.swapaxes(v, 0, 1), 1, tkp)
    bm = varlen_block_map(cu_q, cu_k, tqp, tkp, bq, bk, causal)
    return qt, kt, vt, bm


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _packed_core(q, k, v, cu_q, cu_k, causal, scale, bq, bk, backend):
    out, _res = _packed_core_fwd(q, k, v, cu_q, cu_k, causal, scale,
                                 bq, bk, backend)
    return out


def _packed_core_fwd(q, k, v, cu_q, cu_k, causal, scale, bq, bk,
                     backend):
    tq = q.shape[0]
    qt, kt, vt, bm = _packed_prepare(q, k, v, cu_q, cu_k, causal,
                                     scale, bq, bk)
    if backend == "xla":
        outp, lse = _packed_fwd_xla(qt, kt, vt, bm, scale, causal,
                                    bq, bk)
    else:
        outp, lse = _packed_fwd_pallas(qt, kt, vt, bm, scale, causal,
                                       bq, bk,
                                       interpret=(backend == "interpret"
                                                  or not _on_tpu()))
    out = jnp.swapaxes(outp[:, :tq], 0, 1).astype(q.dtype)
    return out, (q, k, v, cu_q, cu_k, out, lse)


def _packed_core_bwd(causal, scale, bq, bk, backend, res, g):
    q, k, v, cu_q, cu_k, out, lse = res
    tq, h, d = q.shape
    tk = k.shape[0]
    qt, kt, vt, bm = _packed_prepare(q, k, v, cu_q, cu_k, causal,
                                     scale, bq, bk)
    dot_ = _pad_axis(jnp.swapaxes(g, 0, 1), 1, qt.shape[1])
    outp = _pad_axis(jnp.swapaxes(out, 0, 1), 1, qt.shape[1])
    # tpu-lint: ok(X-PROMOTE) -- fp32 softmax accumulator by design
    delta = jnp.sum(dot_.astype(jnp.float32)
                    * outp.astype(jnp.float32), axis=-1)  # [h, tqp]
    if backend == "xla":
        dq, dk, dv = _packed_bwd_xla(qt, kt, vt, dot_, lse, delta, bm,
                                     scale, causal, bq, bk)
    else:
        interp = backend == "interpret" or not _on_tpu()
        dq = _packed_dq_pallas(qt, kt, vt, dot_, lse, delta, bm, scale,
                               causal, bq, bk, interp)
        dk, dv = _packed_dkv_pallas(qt, kt, vt, dot_, lse, delta, bm,
                                    scale, causal, bq, bk, interp)
    dq = jnp.swapaxes(dq[:, :tq], 0, 1).astype(q.dtype)
    dk = jnp.swapaxes(dk[:, :tk], 0, 1).astype(k.dtype)
    dv = jnp.swapaxes(dv[:, :tk], 0, 1).astype(v.dtype)
    return dq, dk, dv, None, None


def _packed_core_fwd_rule(q, k, v, cu_q, cu_k, causal, scale, bq, bk,
                          backend):
    out, res = _packed_core_fwd(q, k, v, cu_q, cu_k, causal, scale,
                                bq, bk, backend)
    return out, res


_packed_core.defvjp(_packed_core_fwd_rule, _packed_core_bwd)


def flash_varlen_packed(q, k, v, cu_seqlens_q, cu_seqlens_k, *,
                        scale=None, causal=False, block_q=None,
                        block_k=None, backend="auto"):
    """Segment-aware block-skipping flash attention over a packed batch.

    q/k/v: ``[total, heads, head_dim]`` raw arrays; ``cu_seqlens_*``:
    int ``[nseg+1]`` cumulative offsets (TRACED operands — one compiled
    program serves every packing of the same shape). Returns
    ``[total_q, heads, head_dim]`` in q's dtype. Differentiable via a
    custom_vjp whose backward kernels walk the same block map.
    """
    bq = int(block_q or DEFAULT_BLOCK_Q)
    bk = int(block_k or DEFAULT_BLOCK_K)
    scale = float(scale if scale is not None else q.shape[-1] ** -0.5)
    backend = _resolve_backend(backend)
    cu_q = jnp.asarray(cu_seqlens_q, jnp.int32)
    cu_k = jnp.asarray(cu_seqlens_k, jnp.int32)
    return _packed_core(q, k, v, cu_q, cu_k, bool(causal), scale, bq,
                        bk, backend)


# ---------------------------------------------------------------------
# Paged variant: chunked prefill / speculative verify attention that
# reads K/V in place from the page-major pool
# ---------------------------------------------------------------------

def _paged_block_k(page_size: int, pages_per_seq: int) -> int:
    """k-tile width for the paged walk: whole pages, ~128 tokens,
    never more pages than the table holds."""
    npp = max(1, min(128 // max(page_size, 1), pages_per_seq))
    return npp * page_size


def _paged_fwd_pallas(qt, key_cache, value_cache, tables, start, klen,
                      scale: float, n_kv: int, bk: int,
                      interpret: bool):
    """qt: [b, n_q, c, d] (kv-major head order); pool
    [P, n_kv, ps, d]; tables [b, pp] ABSOLUTE page ids; start [b]
    chunk position offsets; klen [b] k-tile visit counts.
    Returns [b, n_q, c, d] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n_q, c, d = qt.shape
    _, _, ps, _ = key_cache.shape
    pp = tables.shape[1]
    g = n_q // n_kv
    npp = bk // ps

    def kernel(tables_ref, start_ref, klen_ref, q_ref, k_hbm, v_hbm,
               o_ref, kbuf, vbuf, ksem, vsem):
        i = pl.program_id(0)
        kl = klen_ref[i]
        st = start_ref[i]

        def dmas(j, slot):
            cps = []
            for p in range(npp):
                pidx = jnp.minimum(j * npp + p, jnp.int32(pp - 1))
                pid = tables_ref[i * pp + pidx]
                cps.append(pltpu.make_async_copy(
                    k_hbm.at[pid], kbuf.at[slot, p], ksem.at[slot, p]))
                cps.append(pltpu.make_async_copy(
                    v_hbm.at[pid], vbuf.at[slot, p], vsem.at[slot, p]))
            return cps

        @pl.when(kl > 0)
        def _():
            for cp in dmas(jnp.int32(0), jnp.int32(0)):
                cp.start()

        # tpu-lint: ok(X-PROMOTE) -- fp32 softmax accumulator by design
        qf = q_ref[0].astype(jnp.float32) * jnp.float32(scale)
        q3 = qf.reshape(n_kv, g * c, d)
        pos_q = jax.lax.broadcasted_iota(jnp.int32, (c, bk), 0) + st

        m0 = jnp.full((n_kv, g * c), _NEG, jnp.float32)
        l0 = jnp.zeros((n_kv, g * c), jnp.float32)
        a0 = jnp.zeros((n_kv, g * c, d), jnp.float32)

        def body(j, carry):
            m, l, acc = carry
            slot = jax.lax.rem(j, jnp.int32(2))

            @pl.when(j + 1 < kl)
            def _():
                for cp in dmas(j + 1, jax.lax.rem(j + 1, jnp.int32(2))):
                    cp.start()

            for cp in dmas(j, slot):
                cp.wait()
            # [npp, n_kv, ps, d] pages -> per-head contiguous [bk, d]
            kt = jnp.swapaxes(kbuf[slot], 0, 1).reshape(n_kv, bk, d) \
                .astype(jnp.float32)
            vt = jnp.swapaxes(vbuf[slot], 0, 1).reshape(n_kv, bk, d) \
                .astype(jnp.float32)
            lg = jax.lax.dot_general(
                q3, kt, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)   # [n_kv, g*c, bk]
            interior = (j + 1) * bk - 1 <= st

            def _masked(z):
                pos_k = jax.lax.broadcasted_iota(
                    jnp.int32, (c, bk), 1) + j * bk
                msk = pos_k <= pos_q                  # [c, bk]
                z4 = z.reshape(n_kv * g, c, bk)
                z4 = jnp.where(msk[None], z4, jnp.float32(_NEG))
                return (z4.reshape(n_kv, g * c, bk),
                        jnp.broadcast_to(
                            msk.astype(jnp.float32)[None],
                            (n_kv * g, c, bk))
                        .reshape(n_kv, g * c, bk))

            def _plain(z):
                return z, jnp.ones((n_kv, g * c, bk), jnp.float32)

            lg, mskf = jax.lax.cond(interior, _plain, _masked, lg)
            pm = jnp.maximum(m, lg.max(-1))
            alpha = jnp.exp(m - pm)
            p = jnp.exp(lg - pm[..., None]) * mskf
            l = l * alpha + p.sum(-1)
            pv = jax.lax.dot_general(
                p, vt, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)   # [n_kv, g*c, d]
            acc = acc * alpha[..., None] + pv
            return pm, l, acc

        m, l, acc = jax.lax.fori_loop(jnp.int32(0), kl, body,
                                      (m0, l0, a0))
        out = acc / jnp.maximum(l, jnp.float32(1e-30))[..., None]
        o_ref[0] = out.reshape(n_q, c, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, n_q, c, d), lambda i, *_: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        out_specs=pl.BlockSpec((1, n_q, c, d),
                               lambda i, *_: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, npp, n_kv, ps, d), key_cache.dtype),
            pltpu.VMEM((2, npp, n_kv, ps, d), value_cache.dtype),
            pltpu.SemaphoreType.DMA((2, npp)),
            pltpu.SemaphoreType.DMA((2, npp)),
        ])
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, n_q, c, d), jnp.float32),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(tables.reshape(-1).astype(jnp.int32),
          start.astype(jnp.int32), klen.astype(jnp.int32),
          qt, key_cache, value_cache)
    return out


def _paged_fwd_xla(qt, key_cache, value_cache, tables, start, klen,
                   scale: float, n_kv: int, bk: int):
    """Tiled XLA walk over the pool — one k tile (a few whole pages)
    gathered per step, online softmax. Never materializes the dense
    [b, S, n_kv, d] gather (memory per step is O(b·bk·d))."""
    b, n_q, c, d = qt.shape
    _, _, ps, _ = key_cache.shape
    pp = tables.shape[1]
    g = n_q // n_kv
    npp = bk // ps

    # tpu-lint: ok(X-PROMOTE) -- fp32 softmax accumulator by design
    q5 = (qt.astype(jnp.float32) * jnp.float32(scale)) \
        .reshape(b, n_kv, g, c, d)
    pos_q = start.astype(jnp.int32)[:, None, None] \
        + jax.lax.broadcasted_iota(jnp.int32, (1, c, bk), 1)  # [b,c,bk]
    jmax = jnp.max(klen).astype(jnp.int32)

    def body(j, carry):
        m, l, acc = carry
        # per-page clamp (NOT a clamped slice start — that would shift
        # the whole window and misalign pages with positions on a
        # partial last tile); clamped tail pages sit at positions >= S,
        # which the pos_k mask kills
        page_idx = jnp.clip(j * npp + jnp.arange(npp, dtype=jnp.int32),
                            0, pp - 1)
        pids = jnp.take(tables, page_idx, axis=1)
        kt = key_cache[pids]                  # [b, npp, n_kv, ps, d]
        vt = value_cache[pids]
        kt = jnp.swapaxes(kt, 1, 2).reshape(b, n_kv, bk, d) \
            .astype(jnp.float32)
        vt = jnp.swapaxes(vt, 1, 2).reshape(b, n_kv, bk, d) \
            .astype(jnp.float32)
        # tpu-lint: ok(X-PROMOTE) -- attention scores fp32 by design
        lg = jnp.einsum("bngcd,bnkd->bngck", q5, kt)
        pos_k = jax.lax.broadcasted_iota(jnp.int32, (1, c, bk), 2) \
            + j * bk
        msk = jnp.logical_and(pos_k <= pos_q,
                              (j < klen)[:, None, None])  # [b, c, bk]
        lg = jnp.where(msk[:, None, None], lg, jnp.float32(_NEG))
        pm = jnp.maximum(m, lg.max(-1))
        alpha = jnp.exp(m - pm)
        p = jnp.exp(lg - pm[..., None]) \
            * msk[:, None, None].astype(jnp.float32)
        l = l * alpha + p.sum(-1)
        # tpu-lint: ok(X-PROMOTE) -- fp32 PV accumulation pairs with scores
        pv = jnp.einsum("bngck,bnkd->bngcd", p, vt)
        acc = acc * alpha[..., None] + pv
        return pm, l, acc

    m0 = jnp.full((b, n_kv, g, c), _NEG, jnp.float32)
    l0 = jnp.zeros((b, n_kv, g, c), jnp.float32)
    a0 = jnp.zeros((b, n_kv, g, c, d), jnp.float32)
    nk_static = _cdiv(pp * ps, bk)
    if nk_static <= 4:
        # tiny pools (the CI serving geometry): python-unroll — a
        # per-layer while loop costs more in compile+dispatch than the
        # walk saves when the whole span is a handful of tiles
        carry = (m0, l0, a0)
        for j in range(nk_static):
            carry = body(jnp.int32(j), carry)
        m, l, acc = carry
    else:
        m, l, acc = jax.lax.fori_loop(jnp.int32(0), jmax, body,
                                      (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, n_q, c, d)


def paged_prefill_attention(q, key_cache, value_cache, block_tables,
                            start, *, n_kv: int, scale=None,
                            backend="auto"):
    """Chunk-over-paged-pool attention, reading K/V IN PLACE.

    q: ``[b, c, n_q_heads, d]`` chunk queries at positions
    ``start[b] .. start[b]+c-1``; ``block_tables`` ``[b, pp]`` hold
    ABSOLUTE (layer-offset) page ids; the chunk's own K/V must already
    be written to the pool (the prefill write happens first). Queries
    attend causally: key position <= query position — the cached prefix
    plus the in-chunk triangle, exactly the dense-gather path's mask.
    Returns ``[b, c, n_q_heads, d]`` in q's dtype.
    """
    b, c, n_q, d = q.shape
    _, _, ps, _ = key_cache.shape
    pp = block_tables.shape[1]
    g = n_q // n_kv
    scale = float(scale if scale is not None else d ** -0.5)
    backend = _resolve_backend(backend)
    bk = _paged_block_k(ps, pp)
    S = pp * ps
    # per-row visit count: tiles covering positions <= start + c - 1
    kend_tok = jnp.minimum(start.astype(jnp.int32) + c, S)
    klen = _cdiv(kend_tok, bk).astype(jnp.int32)
    # heads are kv-major (head = kv*g + g_idx, the repo's GQA layout),
    # so [b, n_q, c, d] reshapes to [n_kv, g*c, d] blocks in-kernel
    qt = jnp.swapaxes(q, 1, 2)                          # [b, n_q, c, d]
    if backend == "xla":
        out = _paged_fwd_xla(qt, key_cache, value_cache, block_tables,
                             start, klen, scale, n_kv, bk)
    else:
        out = _paged_fwd_pallas(
            qt, key_cache, value_cache, block_tables, start, klen,
            scale, n_kv, bk,
            interpret=(backend == "interpret" or not _on_tpu()))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)     # [b, c, n_q, d]
