"""Ragged grouped GEMM + no-drop MoE FFN (ROADMAP item 4 tentpole).

The MoE expert bank is really E independent GEMMs over CONTIGUOUS row
segments of a token matrix sorted by expert id — the capacity-factor
GShard einsum the repo carried until now materialized dense
``[T, E, capacity]`` dispatch/combine one-hots instead (O(T·E·C) memory
and FLOPs for what is a ragged gather) and silently shed work at the
capacity bound (``moe.dropped_tokens``). This module is the
megablocks-style replacement (reference comparator: the fork's cutlass
grouped GEMM ``phi/kernels/fusion/cutlass/moe_kernel.cu``; the
FlashAttention-2/CUTLASS case study in PAPERS.md is the Pallas
tiling/pipelining exemplar, and "LLM Inference Acceleration via
Efficient Operation Fusion" grounds fusing the bias/activation tail
into the GEMM):

- :func:`grouped_work_map` — per-expert row intervals come in as a
  TRACED ``offsets`` vector (computed from the gate output with a
  handful of O(T) integer ops) and are compiled OUTSIDE the kernel into
  a static-shape work-unit schedule ``(gids, tids, lo, hi)`` that rides
  into the kernel as scalar-prefetch operands — the same pattern as the
  varlen flash kernel's ``varlen_block_map`` (PR 13). A work unit is
  one (expert, row-tile) visit; row tiles shared by two experts get one
  unit per expert, tiles past the last real row get a phantom unit that
  zero-fills them, so the grid visits ONLY tiles with live rows plus
  the O(E) boundary/pad units.
- :func:`grouped_gemm` — the Pallas kernel: grid ``(nb, nwu)`` with the
  unit axis fastest, per-expert ``[K, bn]`` weight blocks streamed
  double-buffered through their BlockSpec (the same per-dtype block
  geometry as ``stream_linear``), bias add + activation fused on the
  fp32 accumulator in-kernel, and the output tile accumulated across
  the consecutive units that share it (expert-boundary tiles).
- ``custom_vjp`` backward: dx walks the forward map with the per-expert
  weights transposed (the SAME kernel over ``swapaxes(w, 1, 2)``); dw
  accumulates each expert's ``x_rows^T @ dz_rows`` over that expert's
  CONSECUTIVE work units (units are expert-sorted, so the dw output
  block stays resident across them); db is a plain segment-sum.
- Off-TPU the default backend is a math-identical tiled XLA walk that
  visits the same units in the same order with the same fp32
  accumulation — pinned BITWISE-equal to the interpreter-run kernel
  (tests/test_grouped_gemm.py), so CPU CI exercises the exact serving
  numerics.

On top of the kernel, :func:`moe_ffn_nodrop` is the complete no-drop
MoE FFN (fp32 router → stable sort by expert → ragged FFN1/act/FFN2 →
scatter-combine: ZERO capacity padding, ZERO dropped tokens, no
``[T, E, C]`` intermediate anywhere in the trace), and
:func:`moe_ffn_ep` is its expert-parallel twin for the serving mesh —
per-shard token slices exchanged with the expert owners through the
two ``lax.all_to_all`` of the classic EP dispatch/combine (worst-case
per-shard capacity, so EP serving drops nothing either), experts
sharded 1/ep per chip.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ...device.vmem import KERNEL_VMEM_LIMIT_BYTES
from .paged_attention import _enable_x64, _pltpu_compiler_params
from .stream_linear import _apply_activation, _pick_bn

__all__ = [
    "grouped_work_map", "grouped_gemm", "moe_route", "moe_ffn_nodrop",
    "moe_ffn_ep", "DEFAULT_BLOCK_ROWS",
]

#: row-tile height: one MXU-friendly sublane-aligned token block
DEFAULT_BLOCK_ROWS = 128


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


#: numpy (not jnp) on purpose: this module is imported lazily
#: from inside traced functions, and a module-level jnp constant
#: created under an active trace would leak that tracer
_I0 = np.int32(0)


def _i32(v):
    return jnp.asarray(v, jnp.int32)


def _cdiv(a, b):
    return -(-a // b)


def _resolve_backend(backend: str, geometry_ok: bool) -> str:
    if backend == "auto":
        backend = "pallas" if _on_tpu() else "xla"
    if backend not in ("pallas", "interpret", "xla"):
        raise ValueError(
            f"grouped_gemm backend={backend!r}: expected 'auto', "
            "'pallas', 'interpret' or 'xla'")
    if backend != "xla" and not geometry_ok:
        # ragged shapes (N not a multiple of 128) can't tile — the XLA
        # walk is math-identical, so this is a silent-safe fallback
        backend = "xla"
    return backend


# ---------------------------------------------------------------------
# Work-unit map (traced offsets -> static-shape schedule)
# ---------------------------------------------------------------------

def grouped_work_map(offsets, t_pad: int, bm: int):
    """Compile traced per-expert row offsets into the kernel's
    work-unit schedule.

    ``offsets``: int32 ``[E+1]`` cumulative row offsets of the
    expert-sorted token matrix (``offsets[E]`` = real rows, traced).
    ``t_pad``: static padded row count (multiple of ``bm``).

    Returns ``(gids, tids, lo, hi)``, each int32 ``[nwu]`` with
    ``nwu = t_pad//bm + 2*E + 1`` (static): unit ``u`` computes row
    tile ``tids[u]`` against expert ``gids[u]``'s weights, masked to
    global rows ``[lo[u], hi[u])``. Invariants the kernel relies on:
    ``tids`` is non-decreasing (an output tile's visits are
    consecutive), units are expert-sorted (a dw block's visits are
    consecutive), every real expert has >= 1 unit (its dw block is
    always initialized), every tile has >= 1 unit (pad tiles get a
    phantom unit with an empty mask that zero-fills them), and trailing
    inactive units alias the last tile/expert with empty masks.
    """
    offsets = jnp.asarray(offsets, jnp.int32)
    E = offsets.shape[0] - 1
    nm = t_pad // bm
    nwu = nm + 2 * E + 1
    # E real intervals + 1 phantom interval [offsets[E], t_pad)
    ext = jnp.concatenate(
        [offsets, jnp.asarray([t_pad], jnp.int32)])        # [E+2]
    t_lo = ext[:-1] // bm                                  # [E+1]
    t_hi = _cdiv(ext[1:], bm)
    counts = jnp.maximum(t_hi - t_lo, 0)
    # every REAL expert gets >= 1 (possibly empty-masked) unit so its
    # dw output block is zero-initialized even when it owns no rows
    counts = jnp.where(jnp.arange(E + 1) < E,
                       jnp.maximum(counts, 1), counts)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts).astype(jnp.int32)])            # [E+2]
    u = jnp.arange(nwu, dtype=jnp.int32)
    seg = jnp.searchsorted(starts[1:], u, side="right") \
        .astype(jnp.int32)                                 # 0..E+1
    segc = jnp.minimum(seg, E)
    tid = t_lo[segc] + (u - starts[segc])
    active = u < starts[E + 1]
    tid = jnp.clip(jnp.where(active, tid, nm - 1), 0, nm - 1)
    gid = jnp.minimum(segc, E - 1)       # weight index (phantom -> E-1)
    is_real = jnp.logical_and(active, seg < E)
    lo = jnp.where(is_real, ext[segc], 0)
    hi = jnp.where(is_real, ext[segc + 1], 0)
    return (gid.astype(jnp.int32), tid.astype(jnp.int32),
            lo.astype(jnp.int32), hi.astype(jnp.int32))


# ---------------------------------------------------------------------
# Kernels (Pallas; interpret=True is the off-TPU debug path)
# ---------------------------------------------------------------------

def _grouped_fwd_pallas(x_pad, w3, b3, gids, tids, lo, hi, bm, bn,
                        activation, interpret):
    """x_pad [t_pad, K] (rows sorted by expert, zero pad tail),
    w3 [E, K, N], b3 [E, 1, N] f32. Returns [t_pad, N] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_pad, K = x_pad.shape
    N = w3.shape[-1]
    nb = N // bn
    nwu = gids.shape[0]

    def kernel(gids_r, tids_r, lo_r, hi_r, x_ref, w_ref, b_ref, o_ref):
        u = pl.program_id(1)
        rows = tids_r[u] * bm \
            + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        acc = jax.lax.dot_general(
            x_ref[...], w_ref[0].astype(x_ref.dtype),
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)            # [bm, bn]
        acc = acc + b_ref[0].astype(jnp.float32)
        acc = _apply_activation(acc, activation)
        mask = jnp.logical_and(rows >= lo_r[u], rows < hi_r[u])
        contrib = jnp.where(mask, acc, jnp.float32(0.0))
        first = jnp.logical_or(
            u == 0, tids_r[jnp.maximum(u - 1, 0)] != tids_r[u])

        @pl.when(first)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)

        o_ref[...] += contrib

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nb, nwu),
        in_specs=[
            pl.BlockSpec((bm, K), lambda j, u, g, t, lo_, hi_: (t[u], 0)),
            pl.BlockSpec((1, K, bn),
                         lambda j, u, g, t, lo_, hi_: (g[u], 0, j)),
            pl.BlockSpec((1, 1, bn),
                         lambda j, u, g, t, lo_, hi_: (g[u], 0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn),
                               lambda j, u, g, t, lo_, hi_: (t[u], j)),
        scratch_shapes=[])
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((t_pad, N), jnp.float32),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(gids, tids, lo, hi, x_pad, w3, b3)
    return out


def _grouped_fwd_xla(x_pad, w3, b3, gids, tids, lo, hi, bm, bn,
                     activation):
    """Math-identical tiled XLA walk: the SAME (bm, K) x (K, bn) dots
    over the SAME units in the same order, fp32 accumulation from a
    zero output — bitwise-equal to the interpreter-run kernel (every
    non-owning unit contributes an exact +0.0 to a row)."""
    t_pad, K = x_pad.shape
    E, _, N = w3.shape
    nb = N // bn
    nwu = gids.shape[0]
    rows_in_tile = jnp.arange(bm, dtype=jnp.int32)[:, None]

    def unit(u, out):
        tid = tids[u]
        gid = gids[u]
        xt = jax.lax.dynamic_slice(x_pad, (_i32(tid * bm), _I0), (bm, K))
        rows = tid * bm + rows_in_tile
        mask = jnp.logical_and(rows >= lo[u], rows < hi[u])

        def col(j, out):
            wb = jax.lax.dynamic_slice(
                w3, (gid, _I0, _i32(j * bn)), (1, K, bn))[0]
            acc = jax.lax.dot_general(
                xt, wb.astype(xt.dtype), (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)
            acc = acc + jax.lax.dynamic_slice(
                b3, (gid, _I0, _i32(j * bn)), (1, 1, bn))[0].astype(jnp.float32)
            acc = _apply_activation(acc, activation)
            contrib = jnp.where(mask, acc, jnp.float32(0.0))
            cur = jax.lax.dynamic_slice(
                out, (_i32(tid * bm), _i32(j * bn)), (bm, bn))
            return jax.lax.dynamic_update_slice(
                out, cur + contrib, (_i32(tid * bm), _i32(j * bn)))

        return jax.lax.fori_loop(0, nb, col, out)

    out0 = jnp.zeros((t_pad, N), jnp.float32)
    return jax.lax.fori_loop(0, nwu, unit, out0)


def _grouped_dw_pallas(x_pad, dz_pad, gids, tids, lo, hi, bm, bn,
                       interpret):
    """dw[e] = sum over e's rows of x_r^T dz_r. Units are expert-sorted,
    so each expert's [K, bn] output block stays resident across its
    consecutive units; the first unit of each expert zero-initializes
    it (grouped_work_map guarantees every expert has one)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_pad, K = x_pad.shape
    N = dz_pad.shape[-1]
    nb = N // bn
    nwu = gids.shape[0]

    def kernel(gids_r, tids_r, lo_r, hi_r, x_ref, dz_ref, o_ref):
        u = pl.program_id(1)
        rows = tids_r[u] * bm \
            + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        mask = jnp.logical_and(rows >= lo_r[u], rows < hi_r[u])
        xm = jnp.where(mask, x_ref[...], jnp.zeros_like(x_ref))
        contrib = jax.lax.dot_general(
            xm, dz_ref[...], (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)            # [K, bn]
        first = jnp.logical_or(
            u == 0, gids_r[jnp.maximum(u - 1, 0)] != gids_r[u])

        @pl.when(first)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)

        o_ref[...] += contrib[None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nb, nwu),
        in_specs=[
            pl.BlockSpec((bm, K), lambda j, u, g, t, lo_, hi_: (t[u], 0)),
            pl.BlockSpec((bm, bn), lambda j, u, g, t, lo_, hi_: (t[u], j)),
        ],
        out_specs=pl.BlockSpec((1, K, bn),
                               lambda j, u, g, t, lo_, hi_: (g[u], 0, j)),
        scratch_shapes=[])
    return grid_spec, kernel


def _grouped_dw(x_pad, dz_pad, E, gids, tids, lo, hi, bm, bn, backend):
    """Dispatch the dw accumulation (kernel or the identical XLA walk);
    returns [E, K, N] f32."""
    t_pad, K = x_pad.shape
    N = dz_pad.shape[-1]
    if backend == "xla":
        nb = N // bn
        nwu = gids.shape[0]
        rows_in_tile = jnp.arange(bm, dtype=jnp.int32)[:, None]

        def unit(u, dw):
            tid = tids[u]
            gid = gids[u]
            xt = jax.lax.dynamic_slice(x_pad, (_i32(tid * bm), _I0), (bm, K))
            rows = tid * bm + rows_in_tile
            mask = jnp.logical_and(rows >= lo[u], rows < hi[u])
            xm = jnp.where(mask, xt, jnp.zeros_like(xt))

            def col(j, dw):
                dzb = jax.lax.dynamic_slice(
                    dz_pad, (_i32(tid * bm), _i32(j * bn)), (bm, bn))
                contrib = jax.lax.dot_general(
                    xm, dzb, (((0,), (0,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32)
                cur = jax.lax.dynamic_slice(
                    dw, (gid, _I0, _i32(j * bn)), (1, K, bn))
                return jax.lax.dynamic_update_slice(
                    dw, cur + contrib[None], (gid, _I0, _i32(j * bn)))

            return jax.lax.fori_loop(0, nb, col, dw)

        dw0 = jnp.zeros((E, K, N), jnp.float32)
        return jax.lax.fori_loop(0, nwu, unit, dw0)

    from jax.experimental import pallas as pl

    grid_spec, kernel = _grouped_dw_pallas(
        x_pad, dz_pad, gids, tids, lo, hi, bm, bn,
        interpret=(backend == "interpret"))
    from jax.experimental.pallas import tpu as pltpu

    with _enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((E, K, N), jnp.float32),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=(backend == "interpret" or not _on_tpu()),
        )(gids, tids, lo, hi, x_pad, dz_pad)


# ---------------------------------------------------------------------
# Public entry (custom_vjp)
# ---------------------------------------------------------------------

def _geometry(K: int, N: int, itemsize: int):
    """(bm, bn) for the kernel path, or None when N can't tile."""
    bn = _pick_bn(K, N, itemsize)
    return (DEFAULT_BLOCK_ROWS, bn) if bn else None


def _pad_rows(x, t_pad):
    t = x.shape[0]
    if t == t_pad:
        return x
    return jnp.pad(x, ((0, t_pad - t), (0, 0)))


def _raw_grouped(x, w, b, offsets, activation, backend):
    """One ragged grouped GEMM, f32 output [T, N] (no autodiff)."""
    T, K = x.shape
    E, _, N = w.shape
    geo = _geometry(K, N, w.dtype.itemsize)
    backend = _resolve_backend(backend, geo is not None)
    if backend == "xla" and geo is None:
        # un-tileable shapes: same unit walk with bn = N (one column
        # block); bm stays the row tile so the unit schedule is shared
        geo = (DEFAULT_BLOCK_ROWS, N)
    bm, bn = geo
    t_pad = _cdiv(T, bm) * bm
    x_pad = _pad_rows(x, t_pad)
    b3 = b.reshape(E, 1, N).astype(jnp.float32)
    gids, tids, lo, hi = grouped_work_map(offsets, t_pad, bm)
    if backend == "xla":
        out = _grouped_fwd_xla(x_pad, w, b3, gids, tids, lo, hi,
                               bm, bn, activation)
    else:
        out = _grouped_fwd_pallas(
            x_pad, w, b3, gids, tids, lo, hi, bm, bn, activation,
            interpret=(backend == "interpret" or not _on_tpu()))
    return out[:T]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _grouped_core(x, w, b, offsets, activation, backend, out_dtype):
    y, _ = _grouped_core_fwd(x, w, b, offsets, activation, backend,
                             out_dtype)
    return y


def _grouped_core_fwd(x, w, b, offsets, activation, backend, out_dtype):
    y = _raw_grouped(x, w, b, offsets, activation, backend) \
        .astype(out_dtype)
    return y, (x, w, b, offsets)


def _act_fn(activation):
    if activation == "gelu":
        return jax.nn.gelu
    if activation == "relu":
        return jax.nn.relu
    return lambda z: z


def _grouped_core_bwd(activation, backend, out_dtype, res, g):
    x, w, b, offsets = res
    T, K = x.shape
    E, _, N = w.shape
    # tpu-lint: ok(X-PROMOTE) -- fp32 grad accumulation by design
    g32 = g.astype(jnp.float32)
    if activation:
        # recompute the pre-activation with one more grouped GEMM
        # (cheaper than carrying the [T, N] residual through fwd)
        z = _raw_grouped(x, w, b, offsets, None, backend)
        _, act_vjp = jax.vjp(_act_fn(activation), z)
        (dz,) = act_vjp(g32)
    else:
        dz = g32
    # dx walks the forward map against the per-expert transposed bank
    zero_bk = jnp.zeros((E, K), jnp.float32)
    dx = _raw_grouped(dz, jnp.swapaxes(w, 1, 2), zero_bk, offsets,
                      None, backend)
    # dw accumulates per expert segment (expert-sorted units)
    geo = _geometry(K, N, w.dtype.itemsize)
    dwb = _resolve_backend(backend, geo is not None)
    bm, bn = geo if geo is not None else (DEFAULT_BLOCK_ROWS, N)
    t_pad = _cdiv(T, bm) * bm
    gids, tids, lo, hi = grouped_work_map(offsets, t_pad, bm)
    dw = _grouped_dw(_pad_rows(x, t_pad), _pad_rows(dz, t_pad), E,
                     gids, tids, lo, hi, bm, bn, dwb)
    # db: plain per-expert segment sum of dz (rows are expert-sorted)
    row_e = jnp.clip(
        jnp.searchsorted(offsets[1:], jnp.arange(T, dtype=jnp.int32),
                         side="right"), 0, E - 1)
    live = (jnp.arange(T, dtype=jnp.int32)
            < offsets[-1])[:, None].astype(jnp.float32)
    db = jax.ops.segment_sum(dz * live, row_e, num_segments=E)
    return (dx.astype(x.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            None)


def _grouped_core_fwd_rule(x, w, b, offsets, activation, backend,
                           out_dtype):
    return _grouped_core_fwd(x, w, b, offsets, activation, backend,
                             out_dtype)


_grouped_core.defvjp(_grouped_core_fwd_rule, _grouped_core_bwd)


def grouped_gemm(x, w, offsets, *, bias=None, activation=None,
                 out_dtype=None, backend="auto"):
    """Ragged grouped GEMM: ``y[r] = act(x[r] @ w[e(r)] + bias[e(r)])``
    where row ``r``'s expert ``e(r)`` is defined by the sorted-segment
    ``offsets``.

    ``x``: ``[T, K]`` rows SORTED by expert (expert e owns rows
    ``offsets[e]:offsets[e+1]``); ``w``: ``[E, K, N]`` expert bank;
    ``offsets``: int32 ``[E+1]`` TRACED cumulative offsets
    (``offsets[E] <= T``; rows past ``offsets[E]`` produce zeros);
    ``bias``: optional ``[E, N]``. Differentiable in x/w/bias via a
    custom_vjp whose backward walks the same work map. ``backend``:
    ``auto`` (Pallas on TPU, XLA tile walk elsewhere), ``pallas``,
    ``interpret``, ``xla``.
    """
    E, _, N = w.shape
    if offsets.shape[0] != E + 1:
        raise ValueError(
            f"grouped_gemm: offsets has {offsets.shape[0]} entries for "
            f"{E} experts (need E+1)")
    b = bias if bias is not None else jnp.zeros((E, N), jnp.float32)
    if b.ndim == 3:
        b = b.reshape(E, N)
    out_dtype = out_dtype or x.dtype
    return _grouped_core(x, w, b, jnp.asarray(offsets, jnp.int32),
                         activation, backend, out_dtype)


# ---------------------------------------------------------------------
# No-drop MoE FFN (sort -> ragged FFN1/act/FFN2 -> scatter-combine)
# ---------------------------------------------------------------------

def moe_route(x, gate_w, top_k: int):
    """fp32 gate routing: softmax, top-k and the top-k renormalization
    all run in fp32 REGARDLESS of the compute dtype — under AMP a bf16
    router rounds away top-k margins (ties flip expert choice) and a
    bf16 renormalization drifts the combine weights; the router is
    O(T·E), so fp32 here is free next to the expert GEMMs.

    Returns ``(probs [T, E] f32, topk_val [T, K] f32 normalized,
    topk_idx [T, K] int32)``.
    """
    # top-k tie stability under AMP; see the bf16-vs-fp32 parity test
    # tpu-lint: ok(X-PROMOTE) -- fp32 gate routing by design
    logits = jax.lax.dot_general(
        x.astype(jnp.float32), gate_w.astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, -1)
    topk_val, topk_idx = jax.lax.top_k(probs, top_k)
    topk_val = topk_val / jnp.sum(topk_val, -1, keepdims=True)
    return probs, topk_val, topk_idx.astype(jnp.int32)


def _sort_by_expert(topk_idx, E: int):
    """(order [T*K], offsets [E+1], counts [E]) for the expert-sorted
    row layout; ``order`` is a STABLE argsort so same-expert tokens
    keep their batch order (deterministic accumulation)."""
    flat_e = topk_idx.reshape(-1)
    order = jnp.argsort(flat_e, stable=True).astype(jnp.int32)
    counts = jnp.bincount(flat_e, length=E).astype(jnp.int32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts).astype(jnp.int32)])
    return order, offsets, counts


def moe_ffn_nodrop(x, gate_w, w1, b1, w2, b2, *, top_k: int,
                   activation="gelu", backend="auto"):
    """No-drop MoE FFN over flat tokens ``x [T, d]``.

    fp32 router -> tokens stable-sorted by expert id -> TWO ragged
    grouped GEMMs (FFN1 with the activation fused, FFN2) -> unsort +
    gate-weighted combine. Zero capacity padding, zero dropped tokens,
    and no ``[T, E, capacity]`` intermediate exists in the traced
    program (the trace-pin test walks the jaxpr).

    ``w1 [E, d, dff]``, ``b1 [E, dff]`` (or ``[E, 1, dff]``),
    ``w2 [E, dff, d]``, ``b2`` likewise. Returns
    ``(y [T, d] in x.dtype, probs f32, topk_idx, counts [E] int32)`` —
    the extras feed the aux loss and the ``moe.*`` telemetry.
    """
    T, d = x.shape
    E = w1.shape[0]
    probs, topk_val, topk_idx = moe_route(x, gate_w, top_k)
    order, offsets, counts = _sort_by_expert(topk_idx, E)
    # row r of the sorted matrix is token order[r] // K
    x_rows = jnp.take(x, order // top_k, axis=0)           # [T*K, d]
    h = grouped_gemm(x_rows, w1, offsets, bias=b1,
                     activation=activation, backend=backend,
                     out_dtype=x.dtype)
    y_rows = grouped_gemm(h, w2, offsets, bias=b2, backend=backend,
                          out_dtype=jnp.float32)
    # combine: unsort the expert outputs, weight by the normalized
    # gate values, sum the K contributions per token
    y_flat = jnp.zeros((T * top_k, d), jnp.float32) \
        .at[order].set(y_rows)
    y = jnp.sum(y_flat.reshape(T, top_k, d)
                * topk_val[..., None], axis=1)
    return y.astype(x.dtype), probs, topk_idx, counts


# ---------------------------------------------------------------------
# Expert-parallel MoE FFN (inside shard_map over the ep mesh axis)
# ---------------------------------------------------------------------

def moe_ffn_ep(x, gate_w, w1, b1, w2, b2, *, top_k: int, axis: str,
               ep: int, activation="gelu", overlap=None):
    """Expert-parallel MoE FFN for the serving mesh — call INSIDE a
    ``shard_map`` body whose mesh carries the ``axis`` (ep) axis.

    ``x [T, d]`` enters REPLICATED (the serving hidden state); each
    shard slices its ``T/ep`` token block, routes it in fp32, scatters
    the rows into per-expert slot buffers with WORST-CASE per-shard
    capacity ``(T/ep)*K`` (so nothing can ever drop), and exchanges
    with the expert owners through the classic EP pair:

      ``[E, c, d] --all_to_all--> [E/ep, ep*c, d]`` (dispatch)
      local expert FFN (this shard's 1/ep expert slice — the only
      expert weights this chip ever streams)
      ``[E/ep, ep*c, d] --all_to_all--> [E, c, d]`` (combine)

    followed by one ``all_gather`` that restores the replicated hidden
    state for the next layer. The traced collective census of one MoE
    layer is therefore EXACTLY (all_to_all, all_to_all, all_gather) —
    pinned by the EP decode tests and the dryrun_multichip phase.

    ``w1 [E/ep, d, dff]`` etc. are this shard's expert slice (sharded
    by ``TPContext.shard_stack``). Returns ``y [T, d]`` replicated.

    ``overlap`` (default: ``FLAGS_ep_overlap``): double-buffer the
    exchange — the capacity dim splits into two half buffers, BOTH
    dispatch all_to_alls issue before the first expert FFN so buffer
    1's exchange rides under buffer 0's compute, and buffer 0's
    combine issues before buffer 1's FFN. Math-exact (per-slot-row
    GEMMs are independent, halves concatenate back along capacity);
    the census becomes EXACTLY (all_to_all x4, all_gather). Falls
    back to the single-buffer form when the capacity is odd.
    """
    T, d = x.shape
    e_loc = w1.shape[0]
    E = e_loc * ep
    if T % ep:
        raise ValueError(
            f"moe_ffn_ep: {T} tokens not divisible by ep={ep}")
    tl = T // ep
    r = jax.lax.axis_index(axis)
    x_loc = jax.lax.dynamic_slice_in_dim(x, r * tl, tl, 0)
    _, topk_val, topk_idx = moe_route(x_loc, gate_w, top_k)
    order, offsets, _counts = _sort_by_expert(topk_idx, E)
    c = tl * top_k                       # worst case: zero drops
    flat_sorted = jnp.take(topk_idx.reshape(-1), order)
    pos = jnp.arange(tl * top_k, dtype=jnp.int32) \
        - offsets[flat_sorted]
    slot = flat_sorted * c + pos
    x_rows = jnp.take(x_loc, order // top_k, axis=0)
    buf = jnp.zeros((E * c, d), x.dtype).at[slot].set(x_rows) \
        .reshape(E, c, d)
    if overlap is None:
        from ...core.flags import flag
        overlap = bool(flag("ep_overlap"))

    def dispatch(bh):
        # rows for MY experts from every shard, capacities
        # concatenated (the exchange is an all-to-all, not a reduce)
        return jax.lax.all_to_all(bh, axis, split_axis=0,
                                  concat_axis=1, tiled=True)

    def expert_ffn(recv):
        # tpu-lint: ok(X-PROMOTE) -- fp32 expert-GEMM accumulation
        # matches the grouped kernel's accumulator
        h1 = jax.lax.dot_general(
            recv, w1.astype(recv.dtype), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        h1 = _apply_activation(h1 + b1.reshape(e_loc, 1, -1)
                               .astype(jnp.float32), activation) \
            .astype(x.dtype)
        out = jax.lax.dot_general(
            h1, w2.astype(h1.dtype), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return out + b2.reshape(e_loc, 1, -1).astype(jnp.float32)

    def combine(out):
        # reverse exchange back to the token owners
        return jax.lax.all_to_all(out.astype(jnp.float32), axis,
                                  split_axis=1, concat_axis=0,
                                  tiled=True)

    if overlap and c % 2 == 0 and c >= 2:
        from ...profiler import stats as _ep_stats
        _ep_stats.counter("dist.overlap_ep_double_buffer").inc()
        half = c // 2
        # BOTH dispatches issue before the first FFN (buffer 1's
        # exchange rides under buffer 0's compute), and buffer 0's
        # combine issues before buffer 1's FFN — XLA's async collective
        # scheduler overlaps the dataflow-independent pairs
        r0 = dispatch(buf[:, :half])
        r1 = dispatch(buf[:, half:])
        back0 = combine(expert_ffn(r0))
        back1 = combine(expert_ffn(r1))
        back = jnp.concatenate([back0, back1], axis=1)
    else:
        back = combine(expert_ffn(dispatch(buf)))
    y_rows = jnp.take(back.reshape(E * c, d), slot, axis=0)
    y_flat = jnp.zeros((tl * top_k, d), jnp.float32) \
        .at[order].set(y_rows)
    y_loc = jnp.sum(y_flat.reshape(tl, top_k, d)
                    * topk_val[..., None], axis=1)
    # restore the replicated hidden state for the next layer
    y = jax.lax.all_gather(y_loc.astype(x.dtype), axis, axis=0,
                           tiled=True)                   # [T, d]
    return y
