"""Ragged batched-LoRA delta GEMM (ISSUE 18 tentpole kernel).

Batched multi-LoRA serving is the MoE grouped-GEMM problem with a
different bank: K tenants share one base weight stream, and each
token's low-rank delta ``x @ A[s] @ B[s]`` is a ragged grouped matmul
over tokens SORTED BY ADAPTER SLOT — exactly how ``grouped_gemm``
groups tokens by expert (S-LoRA's batched-adapter insight, folded onto
this repo's PR 15 kernel family). This module reuses that machinery
wholesale:

- :func:`sort_by_adapter` mirrors the MoE ``_sort_by_expert``: a
  STABLE argsort of the chunk's per-token adapter-slot ids, except
  BASE-MODEL tokens (slot < 0) sort past every adapter and land after
  ``offsets[-1]`` — the work map already zero-fills rows past the last
  real offset, so base tokens are skipped by construction, not by a
  branch (mixed base+adapter batches cost nothing extra).
- :func:`lora_delta` is ONE ragged launch computing every adapter's
  ``x·A·B`` for all tokens in the chunk: the traced ``offsets`` vector
  compiles into the same static-shape scalar-prefetched work-unit
  schedule (``grouped_work_map``), the grid visits only row tiles with
  live rows, and each unit chains TWO dots — ``[bm, K] x [K, R]`` down
  to the rank, ``[bm, R] x [R, bn]`` back up — with fp32 accumulation
  throughout. Per-adapter dispatch never exists in the trace: adapter
  membership rides the work map, so the compiled-program count is
  independent of which adapters are loaded.
- Ranks are padded to the weight dtype's SUBLANE TILE
  (:func:`pad_rank` — int8: 32, bf16: 16, f32: 8) when the bank is
  built (serving/adapters.py), so the ``[K, R]`` / ``[R, bn]`` blocks
  tile cleanly; padded rank columns are zero and contribute exact
  +0.0.

Off-TPU the default backend is a math-identical tiled XLA walk over
the same units in the same order (the ``grouped_gemm`` discipline), so
CPU CI pins the serving numerics bitwise against the interpreter-run
kernel (tests/test_lora_adapters.py). Inference-only: no custom_vjp —
adapters are served, not trained, here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...device.vmem import KERNEL_VMEM_LIMIT_BYTES
from .grouped_gemm import (_I0, _cdiv, _geometry, _i32, _on_tpu,
                           _pad_rows, _resolve_backend,
                           DEFAULT_BLOCK_ROWS, grouped_work_map)
from .paged_attention import _enable_x64, _pltpu_compiler_params
from .stream_linear import _INT8_SUBLANES, _SUBLANES

__all__ = ["lora_delta", "sort_by_adapter", "inverse_order",
           "pad_rank"]


def pad_rank(rank: int, dtype) -> int:
    """LoRA rank padded up to ``dtype``'s sublane tile (int8: 32,
    bf16: 16, f32: 8) — the bank stores ``[K, R_pad]`` / ``[R_pad, N]``
    so the delta kernel's rank axis tiles cleanly; the padded columns
    are zero and contribute exact +0.0 to the delta."""
    it = jnp.dtype(dtype).itemsize
    sub = _INT8_SUBLANES if it == 1 else _SUBLANES.get(it, 8)
    return _cdiv(int(rank), sub) * sub


def sort_by_adapter(slot_ids, n_slots: int):
    """(order [T], offsets [S+1], counts [S]) for the adapter-sorted
    row layout of one chunk.

    ``slot_ids``: int32 ``[T]`` per-token adapter SLOT index into the
    bank (traced); ``< 0`` (or out of range) marks a BASE-MODEL token.
    ``order`` is a STABLE argsort so same-adapter tokens keep their
    batch order; base tokens sort to the TAIL, past ``offsets[-1]``,
    where :func:`lora_delta`'s work map zero-fills — base tokens are
    skipped without a branch in the trace.
    """
    flat = jnp.asarray(slot_ids, jnp.int32).reshape(-1)
    key = jnp.where(jnp.logical_or(flat < 0, flat >= n_slots),
                    _i32(n_slots), flat)
    order = jnp.argsort(key, stable=True).astype(jnp.int32)
    counts = jnp.bincount(key, length=n_slots + 1)[:n_slots] \
        .astype(jnp.int32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts).astype(jnp.int32)])
    return order, offsets, counts


def inverse_order(order):
    """Inverse permutation: ``inv[order[r]] = r`` — unsorts the delta
    rows back to batch order with one gather."""
    T = order.shape[0]
    return jnp.zeros((T,), jnp.int32).at[order].set(
        jnp.arange(T, dtype=jnp.int32))


# ---------------------------------------------------------------------
# Kernels (Pallas; interpret=True is the off-TPU debug path)
# ---------------------------------------------------------------------

def _lora_fwd_pallas(x_pad, a3, b3, gids, tids, lo, hi, bm, bn,
                     interpret):
    """x_pad [t_pad, K] (rows sorted by adapter, base/pad tail),
    a3 [S, K, R], b3 [S, R, N]. Returns [t_pad, N] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_pad, K = x_pad.shape
    S, _, R = a3.shape
    N = b3.shape[-1]
    nb = N // bn
    nwu = gids.shape[0]

    def kernel(gids_r, tids_r, lo_r, hi_r, x_ref, a_ref, b_ref, o_ref):
        u = pl.program_id(1)
        rows = tids_r[u] * bm \
            + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        # down to the rank, back up — both dots accumulate fp32
        h = jax.lax.dot_general(
            x_ref[...], a_ref[0].astype(x_ref.dtype),
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)            # [bm, R]
        acc = jax.lax.dot_general(
            h, b_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)            # [bm, bn]
        mask = jnp.logical_and(rows >= lo_r[u], rows < hi_r[u])
        contrib = jnp.where(mask, acc, jnp.float32(0.0))
        first = jnp.logical_or(
            u == 0, tids_r[jnp.maximum(u - 1, 0)] != tids_r[u])

        @pl.when(first)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)

        o_ref[...] += contrib

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nb, nwu),
        in_specs=[
            pl.BlockSpec((bm, K), lambda j, u, g, t, lo_, hi_: (t[u], 0)),
            pl.BlockSpec((1, K, R),
                         lambda j, u, g, t, lo_, hi_: (g[u], 0, 0)),
            pl.BlockSpec((1, R, bn),
                         lambda j, u, g, t, lo_, hi_: (g[u], 0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn),
                               lambda j, u, g, t, lo_, hi_: (t[u], j)),
        scratch_shapes=[])
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((t_pad, N), jnp.float32),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(gids, tids, lo, hi, x_pad, a3, b3)
    return out


def _lora_fwd_xla(x_pad, a3, b3, gids, tids, lo, hi, bm, bn):
    """Math-identical tiled XLA walk: the SAME chained
    (bm, K) x (K, R), (bm, R) x (R, bn) dots over the SAME units in
    the same order, fp32 accumulation from a zero output — bitwise-
    equal to the interpreter-run kernel."""
    t_pad, K = x_pad.shape
    S, _, R = a3.shape
    N = b3.shape[-1]
    nb = N // bn
    nwu = gids.shape[0]
    rows_in_tile = jnp.arange(bm, dtype=jnp.int32)[:, None]

    def unit(u, out):
        tid = tids[u]
        gid = gids[u]
        xt = jax.lax.dynamic_slice(x_pad, (_i32(tid * bm), _I0), (bm, K))
        ag = jax.lax.dynamic_slice(a3, (gid, _I0, _I0), (1, K, R))[0]
        h = jax.lax.dot_general(
            xt, ag.astype(xt.dtype), (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)
        rows = tid * bm + rows_in_tile
        mask = jnp.logical_and(rows >= lo[u], rows < hi[u])

        def col(j, out):
            bb = jax.lax.dynamic_slice(
                b3, (gid, _I0, _i32(j * bn)), (1, R, bn))[0]
            # fp32 rank-space delta: h is the fp32 down-projection and
            # B rides up at fp32 so the delta adds exactly onto the base
            # projection's fp32 accumulator.
            # tpu-lint: ok(X-PROMOTE) -- rank-thin [bm,R]x[R,bn] dot: upcast traffic is R/K-th of a base-weight stream
            acc = jax.lax.dot_general(
                h, bb.astype(jnp.float32), (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)
            contrib = jnp.where(mask, acc, jnp.float32(0.0))
            cur = jax.lax.dynamic_slice(
                out, (_i32(tid * bm), _i32(j * bn)), (bm, bn))
            return jax.lax.dynamic_update_slice(
                out, cur + contrib, (_i32(tid * bm), _i32(j * bn)))

        return jax.lax.fori_loop(0, nb, col, out)

    out0 = jnp.zeros((t_pad, N), jnp.float32)
    return jax.lax.fori_loop(0, nwu, unit, out0)


# ---------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------

def lora_delta(x, a, b, offsets, *, out_dtype=None, backend="auto"):
    """ONE ragged grouped launch: ``delta[r] = x[r] @ a[s(r)] @ b[s(r)]``
    for every adapter in the bank, where row ``r``'s adapter ``s(r)``
    is defined by the sorted-segment ``offsets``.

    ``x``: ``[T, K]`` rows SORTED by adapter slot
    (:func:`sort_by_adapter`; slot s owns rows
    ``offsets[s]:offsets[s+1]``); ``a``: ``[S, K, R]`` down-projection
    bank; ``b``: ``[S, R, N]`` up-projection bank (adapter scaling
    ``alpha/r`` folded into ``b`` at load); ``offsets``: int32
    ``[S+1]`` TRACED cumulative offsets — rows past ``offsets[S]``
    (base-model tokens, pad) produce ZERO delta. Returns ``[T, N]`` in
    ``out_dtype`` (default fp32, for adding onto the base projection's
    fp32 accumulator). ``backend``: ``auto`` (Pallas on TPU, XLA tile
    walk elsewhere), ``pallas``, ``interpret``, ``xla``.
    """
    T, K = x.shape
    S, _, R = a.shape
    N = b.shape[-1]
    if offsets.shape[0] != S + 1:
        raise ValueError(
            f"lora_delta: offsets has {offsets.shape[0]} entries for "
            f"{S} adapter slots (need S+1)")
    if b.shape[0] != S or b.shape[1] != R:
        raise ValueError(
            f"lora_delta: bank mismatch a={a.shape} vs b={b.shape} "
            "(need a [S, K, R], b [S, R, N])")
    geo = _geometry(K, N, b.dtype.itemsize)
    backend = _resolve_backend(backend, geo is not None)
    if backend == "xla" and geo is None:
        geo = (DEFAULT_BLOCK_ROWS, N)
    bm, bn = geo
    t_pad = _cdiv(T, bm) * bm
    x_pad = _pad_rows(x, t_pad)
    gids, tids, lo, hi = grouped_work_map(
        jnp.asarray(offsets, jnp.int32), t_pad, bm)
    if backend == "xla":
        out = _lora_fwd_xla(x_pad, a, b, gids, tids, lo, hi, bm, bn)
    else:
        out = _lora_fwd_pallas(
            x_pad, a, b, gids, tids, lo, hi, bm, bn,
            interpret=(backend == "interpret" or not _on_tpu()))
    out = out[:T]
    return out if out_dtype is None else out.astype(out_dtype)
