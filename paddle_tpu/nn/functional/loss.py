"""Loss functionals.

TPU-native equivalent of the reference's loss ops (reference:
python/paddle/nn/functional/loss.py → phi cross_entropy /
softmax_with_cross_entropy kernels). Label-index cross entropy uses
one-hot-free gather of log-probs (XLA lowers take_along_axis efficiently);
reductions follow paddle semantics ('none' | 'mean' | 'sum').
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops.dispatch import eager_apply, as_tensor_args

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "square_error_cost",
    "log_loss", "sigmoid_focal_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "poisson_nll_loss", "gaussian_nll_loss",
    "ctc_loss", "margin_cross_entropy", "huber_loss", "rnnt_loss",
]


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


@jax.custom_vjp
def _fused_index_ce(logits, ids, valid):
    """Per-token softmax cross entropy for index labels, last axis.

    Closed-form custom VJP built from iota-compares and masked
    reductions ONLY — no take_along_axis, no one_hot materialization,
    and no autodiff through max/gather (whose VJPs lower to TPU
    scatters). Measured on bert-base MLM (b32 s512, [16384, 30522]
    bf16 logits): the gather-form CE with autodiff backward cost
    102ms/step — a third of the whole pretraining step
    (tools/bert_profile.py noce ablation, r5); this form is a few
    fused passes over the logits. Reference comparator: the fused
    phi softmax_with_cross_entropy kernel.

    ids must be pre-clamped to [0, V); ``valid`` masks ignored tokens
    (their loss and gradient are exactly 0). Accumulation is fp32; the
    logits array itself is never copied to fp32.
    """
    return _fused_index_ce_fwd(logits, ids, valid)[0]


def _fused_index_ce_fwd(logits, ids, valid):
    m = jnp.max(logits, axis=-1)
    sumexp = jnp.sum(
        jnp.exp((logits - m[..., None]).astype(jnp.float32)), axis=-1)
    eq = (jnp.arange(logits.shape[-1], dtype=ids.dtype)
          == ids[..., None])
    picked = jnp.sum(jnp.where(eq, logits, 0).astype(jnp.float32),
                     axis=-1)
    per = jnp.log(sumexp) + m.astype(jnp.float32) - picked
    return jnp.where(valid, per, 0.0), (logits, ids, valid, m, sumexp)


def _fused_index_ce_bwd(res, g):
    logits, ids, valid, m, sumexp = res
    # d_logits = (softmax - onehot) * g, zeroed on invalid tokens —
    # one fused elementwise pass (exp/compare/sub/mul + bf16 cast)
    gv = jnp.where(valid, g, 0.0)[..., None]
    p = jnp.exp((logits - m[..., None]).astype(jnp.float32)
                - jnp.log(sumexp)[..., None])
    eq = (jnp.arange(logits.shape[-1], dtype=ids.dtype)
          == ids[..., None])
    d = (p - eq.astype(jnp.float32)) * gv
    import numpy as _np

    f0 = lambda a: _np.zeros(a.shape, jax.dtypes.float0)  # noqa: E731
    return d.astype(logits.dtype), f0(ids), f0(valid)


_fused_index_ce.defvjp(_fused_index_ce_fwd, _fused_index_ce_bwd)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Softmax cross entropy (reference: nn/functional/loss.py
    cross_entropy over the phi softmax_with_cross_entropy kernel).

    Label contract (index labels): entries equal to ``ignore_index``
    contribute zero loss and zero gradient, and are excluded from the
    ``'mean'`` denominator. Any OTHER out-of-range entry (negative, or
    >= the class count) is clamped into ``[0, num_classes)`` before the
    gather — the take_along_axis clamp semantics every path of this op
    (including the fused closed-form big-vocab path) preserves. Garbage
    labels therefore train against a clamped boundary class rather than
    silently producing a zero-gradient row; pass ``ignore_index`` for
    tokens that should not contribute.
    """
    has_w = weight is not None
    tensors = as_tensor_args(*((input, label, weight) if has_w
                               else (input, label)))

    return eager_apply("cross_entropy", _cross_entropy_raw, tensors,
                       {"use_softmax": bool(use_softmax),
                        "soft_label": bool(soft_label),
                        "label_smoothing": float(label_smoothing),
                        "ignore_index": int(ignore_index),
                        "reduction": reduction, "axis": int(axis),
                        "has_w": has_w})


def _cross_entropy_raw(logits, lab, *maybe_w, use_softmax=True,
                       soft_label=False, label_smoothing=0.0,
                       ignore_index=-100, reduction="mean", axis=-1,
                       has_w=False):
    # Fast path for plain index-label CE over a big vocab: gather-form
    # with fp32 accumulation inside the reductions. Never materializes
    # a full fp32 logits/log-probs array — for bf16 logits at GPT
    # vocab sizes (51200) the fp32 copies are ~GBs of HBM traffic
    # (reference fuses the same way: phi softmax_with_cross_entropy).
    if (use_softmax and not soft_label and label_smoothing == 0.0
            and not has_w):
        ids = lab.astype(jnp.int32)
        if ids.ndim == logits.ndim:
            ids = jnp.squeeze(ids, axis=axis)
        if axis not in (-1, logits.ndim - 1):
            logits = jnp.moveaxis(logits, axis, -1)
        # clamp to [0, V): the fused op's iota-compare matches NO
        # column for an out-of-range id (silent zero-gradient row);
        # clamping restores the gather path's take_along_axis
        # behavior (see the public docstring's label contract)
        safe_ids = jnp.clip(
            jnp.where(ids == ignore_index, 0, ids),
            0, logits.shape[-1] - 1)
        valid = ids != ignore_index
        per = _fused_index_ce(logits, safe_ids, valid)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
        return _reduce(per, reduction)
    logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
        else jnp.log(jnp.clip(logits, 1e-10))
    nclass = logits.shape[axis]
    if soft_label:
        soft = lab
        if label_smoothing > 0.0:
            soft = soft * (1 - label_smoothing) + label_smoothing / nclass
        per = -jnp.sum(soft * logp, axis=axis)
        return _reduce(per, reduction)
    ids = lab.astype(jnp.int32)
    if ids.ndim == logp.ndim:
        ids = jnp.squeeze(ids, axis=axis)
    safe_ids = jnp.where(ids == ignore_index, 0, ids)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe_ids, axis), axis=axis)
    per = -jnp.squeeze(picked, axis)
    if label_smoothing > 0.0:
        smooth_term = -jnp.mean(logp, axis=axis)
        per = (1 - label_smoothing) * per + label_smoothing * smooth_term
    valid = ids != ignore_index
    if has_w:
        w = maybe_w[0][safe_ids]
        per = per * w
        per = jnp.where(valid, per, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, w, 0.0))
            return jnp.sum(per) / jnp.maximum(denom, 1e-12)
        return _reduce(per, reduction)
    per = jnp.where(valid, per, 0.0)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
        return jnp.sum(per) / denom
    return _reduce(per, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # paddle returns loss with the class axis kept as size-1
    from ...ops import manipulation as _m
    loss = loss.unsqueeze(axis) if hasattr(loss, "unsqueeze") else loss
    if return_softmax:
        from .activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def _mse_loss_raw(a, b, reduction="mean"):
    return _reduce(jnp.square(a - b), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return eager_apply("mse_loss", _mse_loss_raw,
                       as_tensor_args(input, label),
                       {"reduction": reduction})


def _square_error_cost_raw(a, b):
    return jnp.square(a - b)


def square_error_cost(input, label):
    return eager_apply("square_error_cost", _square_error_cost_raw,
                       as_tensor_args(input, label))


def _l1_loss_raw(a, b, reduction="mean"):
    return _reduce(jnp.abs(a - b), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return eager_apply("l1_loss", _l1_loss_raw,
                       as_tensor_args(input, label),
                       {"reduction": reduction})


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    has_w = weight is not None
    tensors = as_tensor_args(*((input, label, weight) if has_w
                               else (input, label)))

    def raw(logp, lab, *maybe_w):
        ids = lab.astype(jnp.int32)
        safe = jnp.where(ids == ignore_index, 0, ids)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        per = -jnp.squeeze(picked, 1)
        valid = ids != ignore_index
        if has_w:
            w = maybe_w[0][safe]
            per = jnp.where(valid, per * w, 0.0)
            if reduction == "mean":
                return jnp.sum(per) / jnp.maximum(
                    jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
        else:
            per = jnp.where(valid, per, 0.0)
            if reduction == "mean":
                return jnp.sum(per) / jnp.maximum(
                    jnp.sum(valid.astype(per.dtype)), 1.0)
        return _reduce(per, reduction)

    return eager_apply("nll_loss", raw, tensors)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    has_w = weight is not None
    tensors = as_tensor_args(*((input, label, weight) if has_w
                               else (input, label)))

    def raw(p, y, *maybe_w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        per = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if maybe_w:
            per = per * maybe_w[0]
        return _reduce(per, reduction)

    return eager_apply("binary_cross_entropy", raw, tensors)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    extra = []
    if weight is not None:
        extra.append(weight)
    if pos_weight is not None:
        extra.append(pos_weight)
    tensors = as_tensor_args(logit, label, *extra)
    has_w = weight is not None
    has_pw = pos_weight is not None

    def raw(z, y, *wp):
        i = 0
        w = None
        pw = None
        if has_w:
            w = wp[i]
            i += 1
        if has_pw:
            pw = wp[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight folding
        if pw is not None:
            log_weight = (pw - 1) * y + 1
            per = (1 - y) * z + log_weight * (
                jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0.0))
        else:
            per = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            per = per * w
        return _reduce(per, reduction)

    return eager_apply("bce_with_logits", raw, tensors)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def raw(logp, y):
        if log_target:
            per = jnp.exp(y) * (y - logp)
        else:
            per = jnp.where(y > 0, y * (jnp.log(jnp.clip(y, 1e-12)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(per) / logp.shape[0]
        return _reduce(per, reduction)

    return eager_apply("kl_div", raw, as_tensor_args(input, label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def raw(a, b):
        diff = jnp.abs(a - b)
        per = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                        diff - 0.5 * delta)
        return _reduce(per, reduction)

    return eager_apply("smooth_l1_loss", raw, as_tensor_args(input, label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def raw(x1, x2, y):
        per = jnp.maximum(0.0, -y * (x1 - x2) + margin)
        return _reduce(per, reduction)

    return eager_apply("margin_ranking_loss", raw,
                       as_tensor_args(input, other, label))


def log_loss(input, label, epsilon=1e-4, name=None):
    def raw(p, y):
        return -(y * jnp.log(p + epsilon) + (1 - y) * jnp.log(1 - p + epsilon))

    return eager_apply("log_loss", raw, as_tensor_args(input, label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    has_n = normalizer is not None
    tensors = as_tensor_args(*((logit, label, normalizer) if has_n
                               else (logit, label)))

    def raw(z, y, *mn):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        mod = jnp.power(1 - p_t, gamma)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        per = a_t * mod * ce
        if mn:
            per = per / mn[0]
        return _reduce(per, reduction)

    return eager_apply("sigmoid_focal_loss", raw, tensors)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def raw(x, y):
        per = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(per, reduction)

    return eager_apply("hinge_embedding_loss", raw, as_tensor_args(input, label))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def raw(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(per, reduction)

    return eager_apply("cosine_embedding_loss", raw,
                       as_tensor_args(input1, input2, label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def raw(a, pos, neg):
        def dist(u, v):
            return jnp.power(
                jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), -1), 1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        per = jnp.maximum(0.0, d_ap - d_an + margin)
        return _reduce(per, reduction)

    return eager_apply("triplet_margin_loss", raw,
                       as_tensor_args(input, positive, negative))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    d_ap = distance_function(input, positive)
    d_an = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        from ...ops import math as _m
        d_an = _m.minimum(d_an, d_pn)

    def raw(dap, dan):
        per = jnp.maximum(0.0, dap - dan + margin)
        return _reduce(per, reduction)

    return eager_apply("triplet_margin_with_distance_loss", raw,
                       as_tensor_args(d_ap, d_an))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    has_w = weight is not None
    tensors = as_tensor_args(*((input, label, weight) if has_w
                               else (input, label)))

    def raw(z, y, *mw):
        per = y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z)
        per = -jnp.mean(per, axis=-1)
        if mw:
            per = per * mw[0]
        return _reduce(per, reduction)

    return eager_apply("multi_label_soft_margin_loss", raw, tensors)


def soft_margin_loss(input, label, reduction="mean", name=None):
    def raw(z, y):
        per = jnp.log1p(jnp.exp(-y * z))
        return _reduce(per, reduction)

    return eager_apply("soft_margin_loss", raw, as_tensor_args(input, label))


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def raw(x, y):
        if log_input:
            per = jnp.exp(x) - y * x
        else:
            per = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            per = per + jnp.where(y > 1, stirling, 0.0)
        return _reduce(per, reduction)

    return eager_apply("poisson_nll_loss", raw, as_tensor_args(input, label))


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def raw(mu, y, var):
        var = jnp.maximum(var, epsilon)
        per = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            per = per + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(per, reduction)

    return eager_apply("gaussian_nll_loss", raw,
                       as_tensor_args(input, label, variance))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC loss (reference: nn/functional/loss.py ctc_loss over the
    warpctc kernel, ops.yaml warpctc). TPU-native: the standard
    log-domain alpha recursion as a ``lax.scan`` over time, vectorized
    across the batch — one compiled program, no host loop.

    log_probs: [max_time, batch, num_classes] (log-softmax applied here
    if the rows don't sum to 1 is NOT checked — pass raw logits and they
    are log-softmaxed, matching the reference's warpctc contract).
    labels: [batch, max_label_len] int padded; lengths as usual.
    """
    def raw(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        lp = jax.nn.log_softmax(lp, axis=-1)
        L = lab.shape[1]
        S = 2 * L + 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        # extended label sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        lab_len = lab_len.astype(jnp.int32)
        in_len = in_len.astype(jnp.int32)
        s_len = 2 * lab_len + 1

        # can we skip from s-2 to s? (ext[s] != blank and != ext[s-2])
        skip_ok = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], 1)

        probs_ext = jnp.take_along_axis(
            jnp.swapaxes(lp, 0, 1), ext[:, None, :].repeat(T, 1),
            axis=2)  # [B, T, S]

        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(probs_ext[:, 0, 0])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, probs_ext[:, 0, 1], neg_inf))

        def step(alpha, t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            a_shift2 = jnp.where(skip_ok, a_shift2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1),
                                   a_shift2)
            new_alpha = merged + probs_ext[:, t, :]
            # frozen past each sequence's input length
            live = (t < in_len)[:, None]
            return jnp.where(live, new_alpha, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        # final: logaddexp of positions s_len-1 and s_len-2
        idx_last = jnp.clip(s_len - 1, 0, S - 1)
        idx_prev = jnp.clip(s_len - 2, 0, S - 1)
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], 1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], 1)[:, 0]
        # zero-length labels have only the all-blank path (s_len == 1):
        # no second terminal state, so don't double-count alpha[:, 0]
        a_prev = jnp.where(s_len >= 2, a_prev, neg_inf)
        ll = jnp.logaddexp(a_last, a_prev)
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(loss.dtype), 1.0)
        return _reduce(loss, reduction)

    return eager_apply("ctc_loss", raw,
                       as_tensor_args(log_probs, labels, input_lengths,
                                      label_lengths))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """ArcFace-family margin softmax (reference: nn/functional/loss.py
    margin_cross_entropy over the margin_cross_entropy kernel): the
    target class's cos(theta) becomes cos(m1*theta + m2) - m3, all
    logits scaled by ``scale``. Under tensor parallelism the sharded
    logits path compiles to the same per-shard max/sum + psum as
    ParallelCrossEntropy."""
    def raw(lg, lb):
        ids = lb.astype(jnp.int32).reshape(-1)
        n, c = lg.shape
        onehot = jax.nn.one_hot(ids, c, dtype=lg.dtype)
        # clamp strictly inside (-1, 1): arccos' is infinite at the
        # boundary, so an exactly-saturated target cosine would emit NaN
        # gradients (the reference kernel clamps the same way)
        eps = 1e-6
        cos = jnp.clip(lg, -1.0 + eps, 1.0 - eps)
        theta = jnp.arccos(cos)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adj = jnp.where(onehot > 0, target, cos) * scale
        m = jnp.max(adj, -1, keepdims=True)
        lse = jnp.log(jnp.sum(jnp.exp(adj - m), -1)) + m[:, 0]
        picked = jnp.sum(adj * onehot, -1)
        loss = lse - picked
        if return_softmax:
            soft = jax.nn.softmax(adj, -1)
            return _reduce(loss, reduction), soft
        return _reduce(loss, reduction)

    n_out = 2 if return_softmax else None
    return eager_apply("margin_cross_entropy", raw,
                       as_tensor_args(logits, label), n_outputs=n_out)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """(ops.yaml huber_loss)"""
    def raw(x, y):
        d = x - y
        ad = jnp.abs(d)
        per = jnp.where(ad <= delta, 0.5 * d * d,
                        delta * (ad - 0.5 * delta))
        return _reduce(per, reduction)

    return eager_apply("huber_loss", raw, as_tensor_args(input, label))


def rnnt_loss(logits, labels, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-Transducer loss (reference: the warprnnt op, ops.yaml; python
    surface paddle.nn.functional.rnnt_loss). TPU-native: the standard
    (t, u) lattice forward recursion as nested ``lax.scan``s —
    sequential over time, sequential over the label axis inside each
    step, vectorized over the batch.

    logits: [B, T, U+1, V] joint-network outputs (T acoustic frames,
    U max label length); labels: [B, U] int padded; lengths as usual.
    """
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: fastemit_lambda != 0 (FastEmit regularization) "
            "is not implemented; pass 0.0")

    def raw(lg, lab, in_len, lab_len):
        B, T, U1, V = lg.shape
        U = U1 - 1
        lp = jax.nn.log_softmax(lg, axis=-1)
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        blank_lp = lp[..., blank]                       # [B, T, U+1]
        lab_i = lab.astype(jnp.int32)
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :], lab_i[:, None, :, None].repeat(T, 1),
            axis=3)[..., 0]                             # [B, T, U]
        in_len = in_len.astype(jnp.int32)
        lab_len = lab_len.astype(jnp.int32)
        u_range = jnp.arange(U1)

        # t = 0 row: only emits along u
        row0 = jnp.concatenate(
            [jnp.zeros((B, 1), lp.dtype),
             jnp.cumsum(emit_lp[:, 0, :], axis=1)], axis=1)
        row0 = jnp.where(u_range[None, :] <= lab_len[:, None], row0,
                         neg_inf)

        def step_t(alpha, t):
            from_blank = alpha + blank_lp[:, t - 1, :]   # stay at u

            def step_u(carry, u):
                v = jnp.logaddexp(
                    from_blank[:, u],
                    carry + emit_lp[:, t, u - 1])
                return v, v

            a0 = from_blank[:, 0]
            _, rest = jax.lax.scan(step_u, a0, jnp.arange(1, U1))
            new = jnp.concatenate([a0[:, None],
                                   jnp.swapaxes(rest, 0, 1)], axis=1)
            new = jnp.where(u_range[None, :] <= lab_len[:, None], new,
                            neg_inf)
            live = (t < in_len)[:, None]   # freeze rows past T_b
            return jnp.where(live, new, alpha), None

        alpha, _ = jax.lax.scan(step_t, row0, jnp.arange(1, T))
        # final: alpha[T_b-1, U_b] + blank emission there
        idx_u = jnp.clip(lab_len, 0, U)[:, None]
        a_fin = jnp.take_along_axis(alpha, idx_u, axis=1)[:, 0]
        t_fin = jnp.clip(in_len - 1, 0, T - 1)
        b_fin = jnp.take_along_axis(
            jnp.take_along_axis(blank_lp, t_fin[:, None, None]
                                .repeat(U1, 2), axis=1)[:, 0, :],
            idx_u, axis=1)[:, 0]
        loss = -(a_fin + b_fin)
        return _reduce(loss, reduction)

    return eager_apply("rnnt_loss", raw,
                       as_tensor_args(logits, labels, input_lengths,
                                      label_lengths))
