"""Normalization functionals.

TPU-native equivalent of the reference's norm ops (reference:
python/paddle/nn/functional/norm.py → phi/kernels/batch_norm_kernel.h,
layer_norm_kernel.h, and the fork's fused_layernorm). Plain jnp math —
XLA fuses the reductions + affine into neighbouring ops, which is the
fusion the reference needs hand-written CUDA for.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops.dispatch import eager_apply, as_tensor_args

__all__ = [
    "batch_norm", "layer_norm", "group_norm", "instance_norm",
    "local_response_norm", "normalize", "rms_norm",
]


def _channel_axis(ndim, data_format):
    return ndim - 1 if data_format[-1] == "C" and len(data_format) > 2 else 1


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    ch_axis = _channel_axis(x.ndim if isinstance(x, Tensor) else x.ndim,
                            data_format)
    use_batch_stats = training and not (use_global_stats is True)

    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(weight)
    if has_b:
        tensors.append(bias)

    if use_batch_stats:
        # running buffers updated in place (momentum smoothing, matching the
        # reference: new = m*old + (1-m)*batch); these updates are
        # stop-gradient by construction (outside the vjp'd raw fn)
        axes = tuple(i for i in range(x.ndim) if i != ch_axis)
        stat_mean = jnp.mean(x._data, axis=axes)
        stat_var = jnp.var(x._data, axis=axes)
        if running_mean is not None:
            running_mean._rebind(
                (momentum * running_mean._data
                 + (1.0 - momentum) * stat_mean).astype(running_mean._data.dtype))
        if running_var is not None:
            n = x.size / stat_mean.size
            unbiased = stat_var * (n / max(n - 1.0, 1.0))
            running_var._rebind(
                (momentum * running_var._data
                 + (1.0 - momentum) * unbiased).astype(running_var._data.dtype))

        return eager_apply("batch_norm", _bn_train_raw,
                           as_tensor_args(*tensors),
                           {"axes": axes, "ch_axis": ch_axis,
                            "epsilon": float(epsilon), "has_w": has_w,
                            "has_b": has_b})

    # eval path: running stats enter as (non-diff) tensor inputs so the
    # raw fn is a stable module-level object — inference-mode batch_norm
    # is admissible to the compiled-forward cache
    tensors = [tensors[0], running_mean, running_var] + tensors[1:]
    return eager_apply("batch_norm", _bn_eval_raw, as_tensor_args(*tensors),
                       {"ch_axis": ch_axis, "epsilon": float(epsilon),
                        "has_w": has_w, "has_b": has_b})


def _bn_train_raw(a, *wb, axes=(), ch_axis=1, epsilon=1e-5, has_w=False,
                  has_b=False):
    # stats recomputed INSIDE the differentiated fn so gradients flow
    # through mean/var (the true BN backward)
    mean = jnp.mean(a, axis=axes)
    var = jnp.var(a, axis=axes)
    shape = [1] * a.ndim
    shape[ch_axis] = a.shape[ch_axis]
    xhat = (a - mean.reshape(shape)) * \
        (1.0 / jnp.sqrt(var + epsilon)).reshape(shape)
    i = 0
    if has_w:
        xhat = xhat * wb[i].reshape(shape)
        i += 1
    if has_b:
        xhat = xhat + wb[i].reshape(shape)
    return xhat.astype(a.dtype)


def _bn_eval_raw(a, rm, rv, *wb, ch_axis=1, epsilon=1e-5, has_w=False,
                 has_b=False):
    shape = [1] * a.ndim
    shape[ch_axis] = a.shape[ch_axis]
    xhat = (a - rm.reshape(shape)) * \
        (1.0 / jnp.sqrt(rv + epsilon)).reshape(shape)
    i = 0
    if has_w:
        xhat = xhat * wb[i].reshape(shape)
        i += 1
    if has_b:
        xhat = xhat + wb[i].reshape(shape)
    return xhat.astype(a.dtype)


def _layer_norm_raw(a, *wb, n_norm=1, epsilon=1e-5, has_w=False,
                    has_b=False):
    axes = tuple(range(a.ndim - n_norm, a.ndim))
    mean = jnp.mean(a, axis=axes, keepdims=True)
    var = jnp.var(a, axis=axes, keepdims=True)
    xhat = (a - mean) / jnp.sqrt(var + epsilon)
    i = 0
    if has_w:
        xhat = xhat * wb[i]
        i += 1
    if has_b:
        xhat = xhat + wb[i]
    return xhat.astype(a.dtype)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_norm = len(tuple(normalized_shape))
    has_w, has_b = weight is not None, bias is not None
    tensors = [x] + ([weight] if has_w else []) + ([bias] if has_b else [])

    return eager_apply("layer_norm", _layer_norm_raw, as_tensor_args(*tensors),
                       {"n_norm": n_norm, "epsilon": float(epsilon),
                        "has_w": has_w, "has_b": has_b})


def _rms_norm_raw(a, *w, epsilon=1e-6, has_w=False):
    ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
    out = a * (1.0 / jnp.sqrt(ms + epsilon)).astype(a.dtype)
    if has_w:
        out = out * w[0]
    return out


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (the fork's LLM path uses fused rmsnorm; here one fused XLA op)."""
    has_w = weight is not None
    tensors = [x] + ([weight] if has_w else [])

    return eager_apply("rms_norm", _rms_norm_raw, as_tensor_args(*tensors),
                       {"epsilon": float(epsilon), "has_w": has_w})


def _group_norm_raw(a, *wb, num_groups=1, epsilon=1e-5, has_w=False,
                    has_b=False):
    n, c = a.shape[0], a.shape[1]
    g = num_groups
    rest = a.shape[2:]
    r = a.reshape((n, g, c // g) + rest)
    axes = tuple(range(2, r.ndim))
    mean = jnp.mean(r, axis=axes, keepdims=True)
    var = jnp.var(r, axis=axes, keepdims=True)
    xhat = ((r - mean) / jnp.sqrt(var + epsilon)).reshape(a.shape)
    shape = [1] * a.ndim
    shape[1] = c
    i = 0
    if has_w:
        xhat = xhat * wb[i].reshape(shape)
        i += 1
    if has_b:
        xhat = xhat + wb[i].reshape(shape)
    return xhat.astype(a.dtype)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    if data_format[-1] == "C" and len(data_format) > 2:
        raise NotImplementedError("group_norm supports NC... layouts")
    has_w, has_b = weight is not None, bias is not None
    tensors = [x] + ([weight] if has_w else []) + ([bias] if has_b else [])

    return eager_apply("group_norm", _group_norm_raw, as_tensor_args(*tensors),
                       {"num_groups": int(num_groups),
                        "epsilon": float(epsilon), "has_w": has_w,
                        "has_b": has_b})


def _instance_norm_raw(a, *wb, eps=1e-5, has_w=False, has_b=False):
    axes = tuple(range(2, a.ndim))
    mean = jnp.mean(a, axis=axes, keepdims=True)
    var = jnp.var(a, axis=axes, keepdims=True)
    xhat = (a - mean) / jnp.sqrt(var + eps)
    shape = [1] * a.ndim
    shape[1] = a.shape[1]
    i = 0
    if has_w:
        xhat = xhat * wb[i].reshape(shape)
        i += 1
    if has_b:
        xhat = xhat + wb[i].reshape(shape)
    return xhat.astype(a.dtype)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    has_w, has_b = weight is not None, bias is not None
    tensors = [x] + ([weight] if has_w else []) + ([bias] if has_b else [])

    return eager_apply("instance_norm", _instance_norm_raw,
                       as_tensor_args(*tensors),
                       {"eps": float(eps), "has_w": has_w, "has_b": has_b})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def raw(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + jnp.take(padded, jnp.arange(i, i + c), axis=1)
        div = jnp.power(k + alpha * acc / size, beta)
        return a / div

    return eager_apply("local_response_norm", raw, as_tensor_args(x))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def raw(a):
        if p == 2:
            norm = jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=True))
        else:
            norm = jnp.power(
                jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True),
                1.0 / p)
        return a / jnp.maximum(norm, epsilon)

    return eager_apply("normalize", raw, as_tensor_args(x))
