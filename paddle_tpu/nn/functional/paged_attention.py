"""Paged-KV block attention — the serving attention path.

TPU-native equivalent of the reference's paged-KV serving kernel
(reference: paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
and the decode kernel family masked_multihead_attention_kernel.cu). The KV
cache lives in fixed-size pages addressed through per-sequence block
tables, so sequences grow without reallocation/copy and memory is shared
across a continuous batch.

On TPU the hot path is the Pallas paged-attention kernel
(jax.experimental.pallas.ops.tpu.paged_attention — MXU-tiled online
softmax reading pages straight from HBM); elsewhere an XLA gather +
masked dense attention computes the same thing (fake-device test
precedent, SURVEY §4).

Layouts (PAGE-MAJOR — r4 redesign):
  q            [batch, num_q_heads, head_dim]        one decode token/seq
  key_cache    [num_pages, page_size, num_kv_heads, head_dim]
  value_cache  [num_pages, page_size, num_kv_heads, head_dim]
  seq_lens     [batch] int32   tokens already in cache (incl. current)
  block_tables [batch, pages_per_seq] int32          page ids per sequence

Why page-major: one page is a CONTIGUOUS [page_size, n_kv, d] block in
the default XLA layout, so (a) the decode scatter writes token rows
in-place with no layout transition, (b) the fused Pallas decode kernel
DMAs whole pages HBM→VMEM, and (c) the XLA gather fallback gathers on
the leading dim. The stock jax paged_attention kernel wants the old
[n_kv, P, ps, d] layout and imposes it on operands, which fought the
scatter's preferred layout (two full-pool copies per layer per token);
it remains available behind FLAGS_paged_attention_backend=pallas via an
explicit transpose.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["paged_attention", "write_kv_pages", "write_prefill_kv_pages"]


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pallas_paged(q, key_cache, value_cache, seq_lens, block_tables):
    """Stock jax kernel path: transpose the page-major pool to the
    [n_kv, P, ps, d] layout it expects (a full-pool copy — opt-in
    only; the fused kernel below is the fast path)."""
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention as kernel,
    )

    key_cache = jnp.transpose(key_cache, (2, 0, 1, 3))
    value_cache = jnp.transpose(value_cache, (2, 0, 1, 3))
    page_size = key_cache.shape[2]
    pages_per_seq = block_tables.shape[1]
    # one compute block ≥ 512 tokens of K keeps the MXU fed
    ppcb = max(1, min(pages_per_seq, 512 // max(page_size, 1)))
    while pages_per_seq % ppcb:
        ppcb -= 1
    # the kernel computes raw q·k logits — fold the 1/sqrt(d) scale into q
    out_dtype = q.dtype
    q = q.astype(jnp.float32) * (q.shape[-1] ** -0.5)
    with jax.enable_x64(False), jax.default_matmul_precision("default"):
        return kernel(
            q, key_cache, value_cache,
            seq_lens.astype(jnp.int32), block_tables.astype(jnp.int32),
            pages_per_compute_block=ppcb,
        ).astype(out_dtype)


def _xla_paged(q, key_cache, value_cache, seq_lens, block_tables):
    b, n_q, d = q.shape
    _, page_size, n_kv, _ = key_cache.shape
    pages_per_seq = block_tables.shape[1]
    max_len = pages_per_seq * page_size

    # gather pages: [b, pages, page, n_kv, d] -> [b, max_len, n_kv, d]
    k = key_cache[block_tables].reshape(b, max_len, n_kv, d)
    v = value_cache[block_tables].reshape(b, max_len, n_kv, d)

    group = n_q // n_kv  # GQA: q heads per kv head
    qh = q.reshape(b, n_kv, group, d)
    logits = jnp.einsum("bngd,bknd->bngk", qh.astype(jnp.float32),
                        k.astype(jnp.float32)) * (d ** -0.5)
    pos = jnp.arange(max_len)
    mask = pos[None, :] < seq_lens[:, None]           # [b, max_len]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bngk,bknd->bngd", w, v.astype(jnp.float32))
    return out.reshape(b, n_q, d).astype(q.dtype)


def _fused_paged(q, key_cache, value_cache, seq_lens, block_tables):
    """Fused Pallas decode attention over the page-major pool.

    One grid program per sequence: pages stream HBM→VMEM through a
    double-buffered async DMA (whole [ps, n_kv, d] blocks — the layout
    is built for this), online-softmax accumulates per page. Unlike the
    XLA gather path this never materializes the gathered K/V (saves a
    full write+read of every attended byte), and unlike the stock jax
    kernel it works WITH the scatter's natural layout instead of
    forcing a transposed pool.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n_q, d = q.shape
    P, ps, n_kv, _ = key_cache.shape
    pp = block_tables.shape[1]
    group = n_q // n_kv
    scale = d ** -0.5
    NEG = -1e30  # python literal: jnp scalars would be captured consts

    def kernel(tables_ref, lens_ref, q_ref, k_hbm, v_hbm, o_ref,
               k_buf, v_buf, k_sem, v_sem):
        i = pl.program_id(0)
        qf = q_ref[0].astype(jnp.float32) \
            * jnp.float32(scale)            # [n_q, d]
        q3 = qf.reshape(n_kv, group, d)

        def _idx(p):
            # explicit lax arithmetic: weak-type promotion on the
            # pallas scalar-ref index recurses in jnp operators
            pi = jax.lax.convert_element_type(p, jnp.int32)
            ii = jax.lax.convert_element_type(i, jnp.int32)
            return jax.lax.add(jax.lax.mul(ii, jnp.int32(pp)), pi)

        def start_dma(p, slot):
            pid = tables_ref[_idx(p)]
            pltpu.make_async_copy(k_hbm.at[pid], k_buf.at[slot],
                                  k_sem.at[slot]).start()
            pltpu.make_async_copy(v_hbm.at[pid], v_buf.at[slot],
                                  v_sem.at[slot]).start()

        def wait_dma(p, slot):
            pid = tables_ref[_idx(p)]
            pltpu.make_async_copy(k_hbm.at[pid], k_buf.at[slot],
                                  k_sem.at[slot]).wait()
            pltpu.make_async_copy(v_hbm.at[pid], v_buf.at[slot],
                                  v_sem.at[slot]).wait()

        start_dma(jnp.int32(0), jnp.int32(0))
        m0 = jnp.full((n_kv, group, 1), NEG, jnp.float32)
        l0 = jnp.zeros((n_kv, group, 1), jnp.float32)
        a0 = jnp.zeros((n_kv, group, d), jnp.float32)

        lens_i = lens_ref[i]

        def body(p, carry):
            m, l, acc = carry
            slot = jax.lax.rem(p, jnp.int32(2))
            nxt = jax.lax.add(p, jnp.int32(1))

            @pl.when(nxt < jnp.int32(pp))
            def _():
                start_dma(nxt, jax.lax.rem(nxt, jnp.int32(2)))

            wait_dma(p, slot)
            # lane-preserving transpose to put the batch (head) dim
            # first: Mosaic requires equal batch dim POSITIONS
            k = jnp.swapaxes(k_buf[slot], 0, 1).astype(jnp.float32)
            v = jnp.swapaxes(v_buf[slot], 0, 1).astype(jnp.float32)
            # [n_kv, group, ps] <- [n_kv, g, d] x [n_kv, ps, d]
            logits = jax.lax.dot_general(
                q3, k, (((2,), (2,)), ((0,), (0,))),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)
            base = jax.lax.mul(jax.lax.convert_element_type(p, jnp.int32),
                               jnp.int32(ps))
            pos = jax.lax.add(
                jax.lax.broadcasted_iota(jnp.int32, (1, 1, ps), 2),
                jax.lax.broadcast(base, (1, 1, ps)))
            valid = jax.lax.lt(
                pos, jax.lax.broadcast(
                    jax.lax.convert_element_type(lens_i, jnp.int32),
                    (1, 1, ps)))
            logits = jnp.where(valid, logits,
                               jnp.float32(NEG))
            pm = jnp.maximum(m, logits.max(-1, keepdims=True))
            alpha = jnp.exp(m - pm)
            w = jnp.exp(logits - pm)                     # [n_kv, g, ps]
            w = jnp.where(valid, w, jnp.float32(0.0))
            l = l * alpha + w.sum(-1, keepdims=True)
            # [n_kv, group, d]
            pv = jax.lax.dot_general(
                w, v, (((2,), (1,)), ((0,), (0,))),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)
            acc = acc * alpha + pv
            return pm, l, acc

        # int32 loop bounds: with x64 enabled (the axon env) python
        # bounds make the index int64, and Mosaic's int64->int32
        # convert lowering recurses forever
        m, l, acc = jax.lax.fori_loop(jnp.int32(0), jnp.int32(pp), body,
                                      (m0, l0, a0))
        out = acc / jnp.maximum(l, jnp.float32(1e-30))
        # f32 out ref: in-kernel f32->bf16 (tpu.truncf) fails to
        # legalize on this toolchain; the caller casts outside
        o_ref[0] = out.reshape(n_q, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, n_q, d), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
        ],
        out_specs=pl.BlockSpec((1, n_q, d), lambda i, *_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, ps, n_kv, d), key_cache.dtype),
            pltpu.VMEM((2, ps, n_kv, d), value_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ])
    # x64 off for the whole kernel trace: the axon env enables x64
    # globally, and weak-typed python scalars become f64/i64 inside the
    # kernel, which Mosaic cannot legalize
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, n_q, d), jnp.float32),
        )(block_tables.reshape(-1).astype(jnp.int32),
          seq_lens.astype(jnp.int32), q, key_cache, value_cache)
    return out.astype(q.dtype)


def paged_attention(q, key_cache, value_cache, seq_lens, block_tables):
    """Single-token decode attention over a paged KV cache.

    Raw-array functional op (used inside compiled decode steps).

    Backend selection (FLAGS_paged_attention_backend: auto|fused|xla|pallas):
    ``auto`` uses the XLA gather+masked-attention path on TPU. Measured
    reason (r4, 1.3B decode): the stock Pallas kernel imposes the
    default ``{3,2,1,0}`` layout on the cache operands while the
    in-place page scatter prefers ``{3,0,2,1}``, so mixing them makes
    XLA insert two full-pool layout copies per layer per token —
    catastrophically slower than the gather it avoids. All-XLA keeps
    one layout end-to-end. The Pallas kernel stays available for
    layouts/configs where it wins (requires head_dim % 128 == 0).
    """
    from ...core.flags import flag

    backend = flag("paged_attention_backend")
    if backend not in ("auto", "fused", "xla", "pallas"):
        raise ValueError(
            f"FLAGS_paged_attention_backend={backend!r}: valid values "
            "are 'auto', 'fused', 'xla', 'pallas'")
    if backend == "pallas":
        return _pallas_paged(q, key_cache, value_cache, seq_lens,
                             block_tables)
    if backend == "fused":
        # hand-written page-DMA kernel: numerically verified, but the
        # per-sequence grid serializes on the single TensorCore and
        # loses to the XLA gather end-to-end on v5e (2019 vs 2531 tok/s
        # on the 1.3B b32 rung; page 32/64 didn't close it) — explicit
        # opt-in only until a multi-sequence-per-program variant wins
        return _fused_paged(q, key_cache, value_cache, seq_lens,
                            block_tables)
    return _xla_paged(q, key_cache, value_cache, seq_lens, block_tables)


def write_kv_pages(key_cache, value_cache, new_k, new_v, positions,
                   block_tables):
    """Scatter one new token's K/V per sequence into the paged cache.

    new_k/new_v: [batch, num_kv_heads, head_dim]; positions: [batch] slot
    index of the new token (0-based). Returns updated caches. The page-
    major layout makes this a natural scatter: indexed dims (page, slot)
    lead, the updated [n_kv, d] rows are contiguous — XLA keeps it in
    place on a loop-carried pool.
    """
    page_size = key_cache.shape[1]
    b = positions.shape[0]
    page_ids = block_tables[jnp.arange(b), positions // page_size]  # [b]
    slots = positions % page_size                                   # [b]
    key_cache = key_cache.at[page_ids, slots].set(
        new_k.astype(key_cache.dtype))
    value_cache = value_cache.at[page_ids, slots].set(
        new_v.astype(value_cache.dtype))
    return key_cache, value_cache


def write_prefill_kv_pages(key_cache, value_cache, k, v, block_tables):
    """Write a whole prompt's K/V ([batch, seq, n_kv, d]) into pages.

    Assumes the prompt starts at position 0 (fresh sequences).
    """
    b, s, n_kv, d = k.shape
    page_size = key_cache.shape[1]
    pos = jnp.arange(s)
    page_ids = block_tables[:, pos // page_size]      # [b, s]
    slots = jnp.broadcast_to(pos % page_size, (b, s))  # [b, s]
    key_cache = key_cache.at[page_ids, slots].set(
        k.astype(key_cache.dtype))
    value_cache = value_cache.at[page_ids, slots].set(
        v.astype(value_cache.dtype))
    return key_cache, value_cache
