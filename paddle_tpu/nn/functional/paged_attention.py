"""Paged-KV block attention — the serving attention path.

TPU-native equivalent of the reference's paged-KV serving kernel
(reference: paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
and the decode kernel family masked_multihead_attention_kernel.cu). The KV
cache lives in fixed-size pages addressed through per-sequence block
tables, so sequences grow without reallocation/copy and memory is shared
across a continuous batch.

On TPU the hot path is the Pallas paged-attention kernel
(jax.experimental.pallas.ops.tpu.paged_attention — MXU-tiled online
softmax reading pages straight from HBM); elsewhere an XLA gather +
masked dense attention computes the same thing (fake-device test
precedent, SURVEY §4).

Layouts (PAGE-MAJOR, head-major pages — r5 redesign):
  q            [batch, num_q_heads, head_dim]        one decode token/seq
  key_cache    [num_pages, num_kv_heads, page_size, head_dim]
  value_cache  [num_pages, num_kv_heads, page_size, head_dim]
  seq_lens     [batch] int32   tokens already in cache (incl. current)
  block_tables [batch, pages_per_seq] int32          page ids per sequence

Why page-major: one page is a CONTIGUOUS [n_kv, page_size, d] block in
the default XLA layout, so (a) the decode scatter writes token rows
in-place with no layout transition, (b) the Pallas decode kernels DMA
whole pages HBM→VMEM, and (c) the XLA gather fallback gathers on the
leading dim. Heads-major WITHIN the page (r5, vs r4's [ps, n_kv, d]):
the streaming decode kernel consumes one kv head at a time, and with
heads outer each per-head slice of a page is a contiguous
[page_size, d] block — the r4 token-major page made that a 256-byte
strided gather that cost ~40% of kernel time (decode ablation r5). The
stock jax paged_attention kernel wants [n_kv, P, ps, d] and imposes it
on operands, which fought the scatter's preferred layout (two full-pool
copies per layer per token); it remains available behind
FLAGS_paged_attention_backend=pallas via an explicit transpose.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...device.vmem import KERNEL_VMEM_LIMIT_BYTES

__all__ = ["paged_attention", "write_kv_pages", "write_prefill_kv_pages"]


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pltpu_memspace(pltpu):
    """Version shim: jax renamed TPUMemorySpace -> MemorySpace (~0.5);
    resolve whichever this runtime ships."""
    return getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace


def _pltpu_compiler_params(pltpu):
    """Version shim: TPUCompilerParams -> CompilerParams (~0.5)."""
    return getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams


def _enable_x64(flag: bool):
    """Version shim: jax.enable_x64 was jax.experimental.enable_x64
    before ~0.5. The x64-off guard protects MOSAIC lowering on TPU
    (f64/i64 leaking into kernels doesn't legalize); in off-TPU
    interpret mode the kernel is ordinary jax ops, and TOGGLING the x64
    context mid-trace breaks older jax (lowered helper subfunctions
    like floor_divide dedup across contexts with mismatched scalar
    dtypes — 'func.call operand type mismatch'), so keep the ambient
    setting there. Also no-op when the config already matches."""
    import contextlib

    if bool(jax.config.jax_enable_x64) == bool(flag) or not _on_tpu():
        return contextlib.nullcontext()
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(flag)
    from jax.experimental import enable_x64 as _ctx

    return _ctx(flag)


def _pallas_paged(q, key_cache, value_cache, seq_lens, block_tables):
    """Stock jax kernel path: transpose the page-major pool to the
    [n_kv, P, ps, d] layout it expects (a full-pool copy — opt-in
    only; the fused kernel below is the fast path)."""
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention as kernel,
    )

    key_cache = jnp.transpose(key_cache, (1, 0, 2, 3))
    value_cache = jnp.transpose(value_cache, (1, 0, 2, 3))
    page_size = key_cache.shape[2]
    pages_per_seq = block_tables.shape[1]
    # one compute block ≥ 512 tokens of K keeps the MXU fed
    ppcb = max(1, min(pages_per_seq, 512 // max(page_size, 1)))
    while pages_per_seq % ppcb:
        ppcb -= 1
    # the kernel computes raw q·k logits — fold the 1/sqrt(d) scale into q
    out_dtype = q.dtype
    q = q.astype(jnp.float32) * (q.shape[-1] ** -0.5)
    with _enable_x64(False), jax.default_matmul_precision("default"):
        return kernel(
            q, key_cache, value_cache,
            seq_lens.astype(jnp.int32), block_tables.astype(jnp.int32),
            pages_per_compute_block=ppcb,
        ).astype(out_dtype)


def _xla_paged(q, key_cache, value_cache, seq_lens, block_tables):
    b, n_q, d = q.shape
    _, n_kv, page_size, _ = key_cache.shape
    pages_per_seq = block_tables.shape[1]
    max_len = pages_per_seq * page_size

    # gather pages on the leading dim: [b, pages, n_kv, page, d];
    # the einsums consume the head-major page layout directly
    k = key_cache[block_tables]
    v = value_cache[block_tables]

    group = n_q // n_kv  # GQA: q heads per kv head
    qh = q.reshape(b, n_kv, group, d)
    # fp32 scores by design (softmax stability; QK reads are KV-bound)
    # tpu-lint: ok(X-PROMOTE) -- attention scores fp32 by design
    logits = jnp.einsum("bngd,bpnsd->bngps", qh.astype(jnp.float32),
                        k.astype(jnp.float32)) * (d ** -0.5)
    logits = logits.reshape(b, n_kv, group, max_len)
    pos = jnp.arange(max_len)
    mask = pos[None, :] < seq_lens[:, None]           # [b, max_len]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(logits, axis=-1) \
        .reshape(b, n_kv, group, pages_per_seq, page_size)
    # tpu-lint: ok(X-PROMOTE) -- fp32 PV accumulation pairs with scores
    out = jnp.einsum("bngps,bpnsd->bngd", w, v.astype(jnp.float32))
    return out.reshape(b, n_q, d).astype(q.dtype)


def _fused_paged(q, key_cache, value_cache, seq_lens, block_tables):
    """Fused Pallas decode attention over the page-major pool.

    One grid program per sequence: pages stream HBM→VMEM through a
    double-buffered async DMA (whole [ps, n_kv, d] blocks — the layout
    is built for this), online-softmax accumulates per page. Unlike the
    XLA gather path this never materializes the gathered K/V (saves a
    full write+read of every attended byte), and unlike the stock jax
    kernel it works WITH the scatter's natural layout instead of
    forcing a transposed pool.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n_q, d = q.shape
    P, n_kv, ps, _ = key_cache.shape
    pp = block_tables.shape[1]
    group = n_q // n_kv
    scale = d ** -0.5
    NEG = -1e30  # python literal: jnp scalars would be captured consts

    def kernel(tables_ref, lens_ref, q_ref, k_hbm, v_hbm, o_ref,
               k_buf, v_buf, k_sem, v_sem):
        i = pl.program_id(0)
        qf = q_ref[0].astype(jnp.float32) \
            * jnp.float32(scale)            # [n_q, d]
        q3 = qf.reshape(n_kv, group, d)

        def _idx(p):
            # explicit lax arithmetic: weak-type promotion on the
            # pallas scalar-ref index recurses in jnp operators
            pi = jax.lax.convert_element_type(p, jnp.int32)
            ii = jax.lax.convert_element_type(i, jnp.int32)
            return jax.lax.add(jax.lax.mul(ii, jnp.int32(pp)), pi)

        def start_dma(p, slot):
            pid = tables_ref[_idx(p)]
            pltpu.make_async_copy(k_hbm.at[pid], k_buf.at[slot],
                                  k_sem.at[slot]).start()
            pltpu.make_async_copy(v_hbm.at[pid], v_buf.at[slot],
                                  v_sem.at[slot]).start()

        def wait_dma(p, slot):
            pid = tables_ref[_idx(p)]
            pltpu.make_async_copy(k_hbm.at[pid], k_buf.at[slot],
                                  k_sem.at[slot]).wait()
            pltpu.make_async_copy(v_hbm.at[pid], v_buf.at[slot],
                                  v_sem.at[slot]).wait()

        start_dma(jnp.int32(0), jnp.int32(0))
        m0 = jnp.full((n_kv, group, 1), NEG, jnp.float32)
        l0 = jnp.zeros((n_kv, group, 1), jnp.float32)
        a0 = jnp.zeros((n_kv, group, d), jnp.float32)

        lens_i = lens_ref[i]

        def body(p, carry):
            m, l, acc = carry
            slot = jax.lax.rem(p, jnp.int32(2))
            nxt = jax.lax.add(p, jnp.int32(1))

            @pl.when(nxt < jnp.int32(pp))
            def _():
                start_dma(nxt, jax.lax.rem(nxt, jnp.int32(2)))

            wait_dma(p, slot)
            # head-major pages: [n_kv, ps, d] already batch-dim-first
            k = k_buf[slot].astype(jnp.float32)
            v = v_buf[slot].astype(jnp.float32)
            # [n_kv, group, ps] <- [n_kv, g, d] x [n_kv, ps, d]
            logits = jax.lax.dot_general(
                q3, k, (((2,), (2,)), ((0,), (0,))),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)
            base = jax.lax.mul(jax.lax.convert_element_type(p, jnp.int32),
                               jnp.int32(ps))
            pos = jax.lax.add(
                jax.lax.broadcasted_iota(jnp.int32, (1, 1, ps), 2),
                jax.lax.broadcast(base, (1, 1, ps)))
            valid = jax.lax.lt(
                pos, jax.lax.broadcast(
                    jax.lax.convert_element_type(lens_i, jnp.int32),
                    (1, 1, ps)))
            logits = jnp.where(valid, logits,
                               jnp.float32(NEG))
            pm = jnp.maximum(m, logits.max(-1, keepdims=True))
            alpha = jnp.exp(m - pm)
            w = jnp.exp(logits - pm)                     # [n_kv, g, ps]
            w = jnp.where(valid, w, jnp.float32(0.0))
            l = l * alpha + w.sum(-1, keepdims=True)
            # [n_kv, group, d]
            pv = jax.lax.dot_general(
                w, v, (((2,), (1,)), ((0,), (0,))),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)
            acc = acc * alpha + pv
            return pm, l, acc

        # int32 loop bounds: with x64 enabled (the axon env) python
        # bounds make the index int64, and Mosaic's int64->int32
        # convert lowering recurses forever
        m, l, acc = jax.lax.fori_loop(jnp.int32(0), jnp.int32(pp), body,
                                      (m0, l0, a0))
        out = acc / jnp.maximum(l, jnp.float32(1e-30))
        # f32 out ref: in-kernel f32->bf16 (tpu.truncf) fails to
        # legalize on this toolchain; the caller casts outside
        o_ref[0] = out.reshape(n_q, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, n_q, d), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        out_specs=pl.BlockSpec((1, n_q, d), lambda i, *_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, n_kv, ps, d), key_cache.dtype),
            pltpu.VMEM((2, n_kv, ps, d), value_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ])
    # x64 off for the whole kernel trace: the axon env enables x64
    # globally, and weak-typed python scalars become f64/i64 inside the
    # kernel, which Mosaic cannot legalize
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, n_q, d), jnp.float32),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
        )(block_tables.reshape(-1).astype(jnp.int32),
          seq_lens.astype(jnp.int32), q, key_cache, value_cache)
    return out.astype(q.dtype)


def build_pool_ownership(block_tables, seq_lens, pool_pages, page_size):
    """Token-level inverse of the block tables: for each token slot of
    one layer's page pool, which batch row owns it and at what position.

    Returns (owner_tok [P*ps] int32 — owning row or -1, pos_tok [P*ps]
    int32 — the token's position in its owner's sequence). Page entries
    whose page-start position is already >= the row's seq_len are
    treated as unallocated padding (block tables are padded with page 0;
    the reserved scratch page must not inherit an owner). Layer-
    independent for the layer-folded pool — compute ONCE per decode
    step and share across layers (the stream kernel's mask operands).
    """
    b, pp = block_tables.shape
    ps = page_size
    jstart = jnp.arange(pp, dtype=jnp.int32)[None, :] * ps    # [1, pp]
    validj = jstart < seq_lens.astype(jnp.int32)[:, None]     # [b, pp]
    # invalid entries are redirected out of range and dropped
    idx = jnp.where(validj, block_tables.astype(jnp.int32),
                    jnp.int32(pool_pages)).ravel()
    rows = jnp.broadcast_to(
        jnp.arange(b, dtype=jnp.int32)[:, None], (b, pp)).ravel()
    pidx = jnp.broadcast_to(
        jnp.arange(pp, dtype=jnp.int32)[None, :], (b, pp)).ravel()
    owner_page = jnp.full((pool_pages,), -1, jnp.int32) \
        .at[idx].set(rows, mode="drop")
    page_index = jnp.zeros((pool_pages,), jnp.int32) \
        .at[idx].set(pidx, mode="drop")
    owner_tok = jnp.repeat(owner_page, ps)
    pos_tok = (jnp.repeat(page_index, ps) * ps
               + jnp.tile(jnp.arange(ps, dtype=jnp.int32), (pool_pages,)))
    return owner_tok, pos_tok


# target token count per stream chunk; the engine rounds its pool
# allocation to a multiple of the resulting page count (see
# inference/engine.py _round_pool_pages, which imports this) so the
# kernels get full-size chunks
STREAM_CHUNK_TOKENS = 1024


def stream_chunk_pages(page_size: int) -> int:
    """Full-target pages-per-chunk for a page size (the pool-size
    rounding quantum)."""
    return max(1, STREAM_CHUNK_TOKENS // max(page_size, 1))


def _pick_chunk_pages(pool_pages: int, page_size: int) -> int:
    """Pages per stream chunk: the largest divisor of the pool size
    whose token count stays near STREAM_CHUNK_TOKENS (DMA blocks of a
    few MB keep the HBM stream saturated; a divisor keeps every block
    in bounds)."""
    for cp in range(min(stream_chunk_pages(page_size), pool_pages),
                    0, -1):
        if pool_pages % cp == 0:
            return cp
    return 1


def _stream_paged(q, key_cache, value_cache, seq_lens, block_tables,
                  pool_base=None, pool_pages=None, ownership=None):
    """Pool-STREAMING Pallas decode attention (the r5 winning design).

    The r4 fused kernel gridded one SEQUENCE per program: 32 seqs x 17
    pages of scalar-driven DMAs with tiny [1, d] x [ps, d] dots — it
    serialized on the single TensorCore and lost to the XLA gather.
    This kernel inverts the loop: the sequential grid walks the LAYER'S
    WHOLE PAGE POOL in multi-page chunks (BlockSpec-driven, so Pallas
    double-buffers the HBM stream automatically), and every chunk is
    one batched MXU matmul for ALL sequences at once —
    [n_kv, b*g, d] x [n_kv, C, d] -> [n_kv, b*g, C] logits, masked by
    token ownership (which row owns each pool slot), online-softmax
    accumulated in VMEM scratch across chunks. Each KV byte is read
    exactly once, in perfectly sequential HBM order, with zero gather
    materialization (the XLA path writes + re-reads a gathered copy of
    every attended byte).

    Design target: the reference's dedicated decode kernels
    (paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu,
    block_multi_head_attention_kernel.cu) — same job, TPU-shaped.

    pool_base: first physical page of this layer's region in a layer-
    folded pool (block_tables hold LAYER-LOCAL logical page ids).
    ownership: optional precomputed (owner_tok, pos_tok) from
    build_pool_ownership — pass it from the decode loop so the 24
    layers share one computation.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n_q, d = q.shape
    _, n_kv, ps, _ = key_cache.shape
    P = int(pool_pages) if pool_pages is not None else key_cache.shape[0]
    g = n_q // n_kv
    bg = b * g
    scale = d ** -0.5
    NEG = -1e30

    cp = _pick_chunk_pages(P, ps)
    C = cp * ps
    nchunks = P // cp

    if ownership is None:
        ownership = build_pool_ownership(block_tables, seq_lens, P, ps)
    owner_tok, pos_tok = ownership
    # full [b, tokens] validity mask, computed in XLA (one fused
    # compare, ~P*ps*b int32) and streamed per chunk as a [1, b, C]
    # block — satisfies Mosaic tiling, and the kernel does zero mask
    # arithmetic
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    valid_full = ((owner_tok[None, :] == rows)
                  & (pos_tok[None, :]
                     < seq_lens.astype(jnp.int32)[:, None]))
    mask3 = jnp.transpose(
        valid_full.astype(jnp.int32).reshape(b, nchunks, C), (1, 0, 2))

    # q -> [n_kv, b*g, d] in the kernel's batched-dot layout (transpose
    # done once here in XLA, not per chunk in the kernel)
    qt = jnp.transpose(q.reshape(b, n_kv, g, d), (1, 0, 2, 3)) \
        .reshape(n_kv, bg, d).astype(key_cache.dtype)

    # layer base in chunk units (pool_base = l * P and cp | P -> exact);
    # pool_base may be a traced loop index
    base_chunk = jnp.reshape(
        jnp.asarray(0 if pool_base is None else pool_base, jnp.int32)
        // jnp.int32(cp), (1,))

    def kernel(base_ref, q_ref, mask_ref, k_ref, v_ref, o_ref,
               m_ref, l_ref, acc_ref):
        c = pl.program_id(0)

        @pl.when(c == 0)
        def _():
            m_ref[...] = jnp.full((n_kv, bg), NEG, jnp.float32)
            l_ref[...] = jnp.zeros((n_kv, bg), jnp.float32)
            acc_ref[...] = jnp.zeros((n_kv, bg, d), jnp.float32)

        valid = mask_ref[0] != 0                         # [b, C]
        if g > 1:
            valid = jnp.repeat(valid, g, axis=0)         # [bg, C]

        # head loop (python-unrolled): with heads OUTER in the page
        # layout, each slice is one contiguous [C, d] block — no
        # relayout, no strided gather (both measured 40-60% of kernel
        # time in the r5 decode ablation)
        for h in range(n_kv):
            k_h = k_ref[:, h].reshape(C, d)
            v_h = v_ref[:, h].reshape(C, d)
            logits = jax.lax.dot_general(
                q_ref[h], k_h, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32) * jnp.float32(scale)
            logits = jnp.where(valid, logits, jnp.float32(NEG))
            m = m_ref[h]
            pm = jnp.maximum(m, logits.max(-1))          # [bg]
            alpha = jnp.exp(m - pm)
            w = jnp.exp(logits - pm[:, None])            # [bg, C]
            w = jnp.where(valid, w, jnp.float32(0.0))
            l_ref[h] = l_ref[h] * alpha + w.sum(-1)
            pv = jax.lax.dot_general(
                w.astype(v_h.dtype), v_h, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)      # [bg, d]
            acc_ref[h] = acc_ref[h] * alpha[:, None] + pv
            m_ref[h] = pm

        @pl.when(c == nchunks - 1)
        def _():
            o_ref[...] = acc_ref[...] / jnp.maximum(
                l_ref[...], jnp.float32(1e-30))[..., None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((n_kv, bg, d), lambda c, base: (0, 0, 0)),
            pl.BlockSpec((1, b, C), lambda c, base: (c, 0, 0)),
            pl.BlockSpec((cp, n_kv, ps, d),
                         lambda c, base: (base[0] + c, 0, 0, 0)),
            pl.BlockSpec((cp, n_kv, ps, d),
                         lambda c, base: (base[0] + c, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((n_kv, bg, d), lambda c, base: (0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_kv, bg), jnp.float32),
            pltpu.VMEM((n_kv, bg), jnp.float32),
            pltpu.VMEM((n_kv, bg, d), jnp.float32),
        ])
    # x64 off for the whole trace (axon enables x64 globally; weak-typed
    # python scalars would become f64/i64 inside the kernel); interpret
    # mode off-TPU so the kernel's numerics are testable on CPU
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n_kv, bg, d), jnp.float32),
            # double-buffered multi-MB stream chunks overflow the
            # conservative 16MB default scoped-VMEM budget; v5e has
            # 128MB physical
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=not _on_tpu(),
        )(base_chunk, qt, mask3, key_cache, value_cache)
    out = jnp.transpose(out.reshape(n_kv, b, g, d), (1, 0, 2, 3))
    return out.reshape(b, n_q, d).astype(q.dtype)


def paged_decode_attention_inplace(q, new_k, new_v, key_cache,
                                   value_cache, seq_lens, block_tables,
                                   pool_base=None, pool_pages=None,
                                   ownership=None):
    """Fused KV-append + pool-streaming decode attention, IN PLACE.

    One Pallas kernel per layer does what the reference's
    masked_multihead_attention_kernel.cu does on GPU: append the current
    token's K/V to the paged cache AND attend over it. Returns
    (out [b, n_q, d], key_cache', value_cache') with the pools aliased
    in place (``input_output_aliases``).

    Why fusion is load-bearing on TPU (r5 HLO diagnosis): with a
    separate XLA scatter in the decode loop, layout assignment pins the
    loop-carried pool to the scatter's preferred token-major physical
    layout while the Pallas custom call constrains the default
    head-major layout — XLA inserts two FULL-POOL copies per layer per
    token (measured 2502 -> 281 tok/s end-to-end). Fused, the pool is
    touched only by this kernel, so it stays in the default layout and
    is never copied.

    Mechanics: the sequential grid walks the layer's page region in
    multi-page chunks (manual double-buffered chunk DMA); every chunk
    is one batched-per-head MXU matmul for ALL sequences with an
    ownership mask, online-softmax accumulated in VMEM. The current
    token's K/V arrive as OPERANDS: they join the softmax as a virtual
    chunk (diagonal mask), while 2b small DMAs write them into their
    page slots concurrently — the streamed reads of those rows are
    masked out (where-before-max also kills any NaN garbage), so the
    write/read race is benign and the writes land before the kernel
    returns (waited on the last chunk).

    seq_lens = tokens already cached EXCLUDING the current token (the
    current token's write position, and its softmax entry comes from
    the operand, not the pool).

    Precondition: every row needs a free slot, i.e.
    ``seq_lens[i] < block_tables.shape[1] * page_size``. An exactly-full
    sequence has nowhere to append; rather than let the clamped
    ``lens // page_size`` index silently overwrite slot
    ``lens % page_size`` of the row's LAST allocated page (HBM cache
    corruption), overfull rows get a MASKED NO-OP write: the page
    read-modify-write runs with an all-zero slot selector, writing back
    identical bytes. The attention output for such a row still folds in
    the operand K/V (the current token attends to itself) but the pool
    is untouched — the caller must grow the table before retrying.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n_q, d = q.shape
    _, n_kv, ps, _ = key_cache.shape
    P = int(pool_pages) if pool_pages is not None else key_cache.shape[0]
    g = n_q // n_kv
    bg = b * g
    scale = d ** -0.5
    NEG = -1e30

    cp = _pick_chunk_pages(P, ps)
    C = cp * ps
    nchunks = P // cp

    if ownership is None:
        ownership = build_pool_ownership(block_tables, seq_lens, P, ps)
    owner_tok, pos_tok = ownership
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    valid_full = ((owner_tok[None, :] == rows)
                  & (pos_tok[None, :]
                     < seq_lens.astype(jnp.int32)[:, None]))
    mask3 = jnp.transpose(
        valid_full.astype(jnp.int32).reshape(b, nchunks, C), (1, 0, 2))

    qt = jnp.transpose(q.reshape(b, n_kv, g, d), (1, 0, 2, 3)) \
        .reshape(n_kv, bg, d).astype(key_cache.dtype)
    # two views of the current K/V: [n_kv, b, d] for the compute slices,
    # [b, n_kv, d] for the page patch (broadcast over slots)
    nk_t = jnp.swapaxes(new_k, 0, 1).astype(key_cache.dtype)
    nv_t = jnp.swapaxes(new_v, 0, 1).astype(value_cache.dtype)
    # page-shaped broadcast for the patch select (Mosaic can't insert a
    # sub-minor dim on 16-bit values in-kernel)
    nk_w = jnp.broadcast_to(new_k.astype(key_cache.dtype)[:, :, None, :],
                            (b, n_kv, ps, d))
    nv_w = jnp.broadcast_to(
        new_v.astype(value_cache.dtype)[:, :, None, :], (b, n_kv, ps, d))

    base = jnp.asarray(0 if pool_base is None else pool_base, jnp.int32)
    lens_i = seq_lens.astype(jnp.int32)
    # seq_lens < pages_per_seq*page_size guard (see docstring): overfull
    # rows clamp their write-page index in range and zero their slot
    # selector, turning the page RMW into a no-op write-back
    pp = block_tables.shape[1]
    overfull = lens_i >= jnp.int32(pp * ps)                # [b]
    wpages = (jnp.take_along_axis(
        block_tables.astype(jnp.int32),
        jnp.minimum(lens_i // ps, pp - 1)[:, None],
        axis=1)[:, 0] + base)                              # [b] abs page
    # slot selector as a 4-D f32 operand (single-slot DMA slices violate
    # Mosaic's sublane tiling — the kernel read-modify-writes WHOLE
    # pages and blends the slot row arithmetically; f32 because Mosaic
    # supports only 32-bit sub-minor broadcasts, and pre-shaped 4-D
    # because i1/bf16 dim insertion doesn't lower)
    slotmask = ((jnp.arange(ps, dtype=jnp.int32)[None, :]
                 == (lens_i % ps)[:, None])
                & ~overfull[:, None]) \
        .astype(jnp.float32)[:, None, :, None]           # [b,1,ps,1]
    scalars = jnp.concatenate(
        [jnp.reshape(base // jnp.int32(cp), (1,)), wpages])

    def kernel(s_ref, q_ref, mask_ref, nk_ref, nv_ref, nkw_ref, nvw_ref,
               sm_ref, k_in, v_in, o_ref, k_hbm, v_hbm,
               kb, vb, pgk, pgv, m_ref, l_ref, acc_ref, rsem, pin_sem,
               pout_sem):
        c = pl.program_id(0)
        base_c = s_ref[0]

        def chunk_copy(idx, slot):
            return (
                pltpu.make_async_copy(
                    k_hbm.at[pl.ds((base_c + idx) * cp, cp)],
                    kb.at[slot], rsem.at[slot, 0]),
                pltpu.make_async_copy(
                    v_hbm.at[pl.ds((base_c + idx) * cp, cp)],
                    vb.at[slot], rsem.at[slot, 1]))

        def page_in(i):
            pid = s_ref[1 + i]
            return (
                pltpu.make_async_copy(k_hbm.at[pid], pgk.at[i],
                                      pin_sem.at[i, 0]),
                pltpu.make_async_copy(v_hbm.at[pid], pgv.at[i],
                                      pin_sem.at[i, 1]))

        def page_out(i):
            pid = s_ref[1 + i]
            return (
                pltpu.make_async_copy(pgk.at[i], k_hbm.at[pid],
                                      pout_sem.at[i, 0]),
                pltpu.make_async_copy(pgv.at[i], v_hbm.at[pid],
                                      pout_sem.at[i, 1]))

        @pl.when(c == 0)
        def _():
            m_ref[...] = jnp.full((n_kv, bg), NEG, jnp.float32)
            l_ref[...] = jnp.zeros((n_kv, bg), jnp.float32)
            acc_ref[...] = jnp.zeros((n_kv, bg, d), jnp.float32)
            for cpy in chunk_copy(jnp.int32(0), jnp.int32(0)):
                cpy.start()
            # current token's K/V: read-modify-write each row's page
            # (whole-page DMAs; the slot row is patched by vector
            # select). Page-outs overlap the stream — raced reads see
            # identical bytes except the masked current row — and are
            # waited on the last chunk.
            for i in range(b):
                for cpy in page_in(i):
                    cpy.start()
            for i in range(b):
                for cpy in page_in(i):
                    cpy.wait()
            sel = sm_ref[...]                            # [b,1,ps,1] f32
            inv = jnp.float32(1.0) - sel
            pgk[...] = (pgk[...].astype(jnp.float32) * inv
                        + nkw_ref[...].astype(jnp.float32) * sel) \
                .astype(pgk.dtype)
            pgv[...] = (pgv[...].astype(jnp.float32) * inv
                        + nvw_ref[...].astype(jnp.float32) * sel) \
                .astype(pgv.dtype)
            for i in range(b):
                for cpy in page_out(i):
                    cpy.start()

        @pl.when(c + 1 < nchunks)
        def _():
            for cpy in chunk_copy(c + 1, jax.lax.rem(c + 1,
                                                     jnp.int32(2))):
                cpy.start()

        slot = jax.lax.rem(c, jnp.int32(2))
        for cpy in chunk_copy(c, slot):
            cpy.wait()

        valid = mask_ref[0] != 0                         # [b, C]
        if g > 1:
            valid = jnp.repeat(valid, g, axis=0)         # [bg, C]

        # current-token virtual chunk: row i attends to operand column i
        diag = (jax.lax.broadcasted_iota(jnp.int32, (bg, b), 0) // g
                == jax.lax.broadcasted_iota(jnp.int32, (bg, b), 1))
        last = c == nchunks - 1

        for h in range(n_kv):
            k_h = kb[slot, :, h].reshape(C, d)
            v_h = vb[slot, :, h].reshape(C, d)
            logits = jax.lax.dot_general(
                q_ref[h], k_h, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32) * jnp.float32(scale)
            logits = jnp.where(valid, logits, jnp.float32(NEG))
            m = m_ref[h]
            pm = jnp.maximum(m, logits.max(-1))          # [bg]
            alpha = jnp.exp(m - pm)
            w = jnp.exp(logits - pm[:, None])            # [bg, C]
            w = jnp.where(valid, w, jnp.float32(0.0))
            l_h = l_ref[h] * alpha + w.sum(-1)
            pv = jax.lax.dot_general(
                w.astype(v_h.dtype), v_h, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)      # [bg, d]
            acc_h = acc_ref[h] * alpha[:, None] + pv
            m_ref[h] = pm
            l_ref[h] = l_h
            acc_ref[h] = acc_h

        @pl.when(c == nchunks - 1)
        def _():
            # fold in the current token from the operands, normalize
            for h in range(n_kv):
                lc = jax.lax.dot_general(
                    q_ref[h], nk_ref[h], (((1,), (1,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32) \
                    * jnp.float32(scale)                 # [bg, b]
                lc = jnp.where(diag, lc, jnp.float32(NEG))
                m = m_ref[h]
                pm = jnp.maximum(m, lc.max(-1))
                alpha = jnp.exp(m - pm)
                wc = jnp.exp(lc - pm[:, None])
                wc = jnp.where(diag, wc, jnp.float32(0.0))
                l_h = l_ref[h] * alpha + wc.sum(-1)
                pv = jax.lax.dot_general(
                    wc.astype(nv_ref.dtype), nv_ref[h],
                    (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32)
                acc_h = acc_ref[h] * alpha[:, None] + pv
                o_ref[h] = acc_h / jnp.maximum(
                    l_h, jnp.float32(1e-30))[:, None]
            for i in range(b):
                for cpy in page_out(i):
                    cpy.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((n_kv, bg, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((1, b, C), lambda c, s: (c, 0, 0)),
            pl.BlockSpec((n_kv, b, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((n_kv, b, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((b, n_kv, ps, d), lambda c, s: (0, 0, 0, 0)),
            pl.BlockSpec((b, n_kv, ps, d), lambda c, s: (0, 0, 0, 0)),
            pl.BlockSpec((b, 1, ps, 1), lambda c, s: (0, 0, 0, 0)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        out_specs=[
            pl.BlockSpec((n_kv, bg, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, cp, n_kv, ps, d), key_cache.dtype),
            pltpu.VMEM((2, cp, n_kv, ps, d), value_cache.dtype),
            pltpu.VMEM((b, n_kv, ps, d), key_cache.dtype),
            pltpu.VMEM((b, n_kv, ps, d), value_cache.dtype),
            pltpu.VMEM((n_kv, bg), jnp.float32),
            pltpu.VMEM((n_kv, bg), jnp.float32),
            pltpu.VMEM((n_kv, bg, d), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((b, 2)),
            pltpu.SemaphoreType.DMA((b, 2)),
        ])
    with _enable_x64(False):
        out, ck, cv = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((n_kv, bg, d), jnp.float32),
                jax.ShapeDtypeStruct(key_cache.shape, key_cache.dtype),
                jax.ShapeDtypeStruct(value_cache.shape,
                                     value_cache.dtype),
            ],
            # inputs are numbered with the scalar-prefetch operand as 0:
            # key_cache is arg 8, value_cache arg 9 -> outputs 1, 2
            input_output_aliases={8: 1, 9: 2},
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=not _on_tpu(),
        )(scalars, qt, mask3, nk_t, nv_t, nk_w, nv_w, slotmask,
          key_cache, value_cache)
    out = jnp.transpose(out.reshape(n_kv, b, g, d), (1, 0, 2, 3))
    return out.reshape(b, n_q, d).astype(q.dtype), ck, cv


def paged_attention(q, key_cache, value_cache, seq_lens, block_tables,
                    pool_base=None, pool_pages=None, ownership=None):
    """Single-token decode attention over a paged KV cache.

    Raw-array functional op (used inside compiled decode steps).
    ``pool_base``/``pool_pages`` describe a layer-folded pool: the
    block_tables hold LAYER-LOCAL page ids and the layer's region
    starts at physical page ``pool_base`` (defaults: whole pool).

    Backend selection (FLAGS_paged_attention_backend:
    auto|stream|fused|xla|pallas): ``auto`` uses the pool-streaming
    Pallas kernel on TPU when its layout constraints hold (head_dim a
    lane multiple, layer region a whole number of stream chunks) and
    the XLA gather+masked-attention path otherwise. The r4 measured
    ranking (stock jax kernel forces a pool relayout the scatter hates;
    the per-sequence fused kernel serializes) is documented on each
    backend's function.
    """
    from ...core.flags import flag

    backend = flag("paged_attention_backend")
    if backend not in ("auto", "stream", "fused", "xla", "pallas"):
        raise ValueError(
            f"FLAGS_paged_attention_backend={backend!r}: valid values "
            "are 'auto', 'stream', 'fused', 'xla', 'pallas'")
    P = int(pool_pages) if pool_pages is not None else key_cache.shape[0]
    base = 0 if pool_base is None else pool_base
    if backend == "auto":
        d = q.shape[-1]
        backend = "stream" if (_on_tpu() and d % 128 == 0
                               and pool_base is not None) else "xla"
    if backend == "stream":
        if q.shape[-1] % 128 != 0:
            raise ValueError(
                "paged_attention backend 'stream' requires head_dim to "
                f"be a multiple of 128 (lane width); got {q.shape[-1]}. "
                "Use 'auto' to fall back automatically.")
        return _stream_paged(q, key_cache, value_cache, seq_lens,
                             block_tables, pool_base=pool_base,
                             pool_pages=pool_pages, ownership=ownership)
    abs_tables = block_tables + base if pool_base is not None \
        else block_tables
    if backend == "pallas":
        return _pallas_paged(q, key_cache, value_cache, seq_lens,
                             abs_tables)
    if backend == "fused":
        # r4 kernel: one sequence per grid program — numerically
        # verified but serializes on the single TensorCore and loses to
        # the XLA gather end-to-end (2019 vs 2531 tok/s, 1.3B b32);
        # kept for comparison
        return _fused_paged(q, key_cache, value_cache, seq_lens,
                            abs_tables)
    return _xla_paged(q, key_cache, value_cache, seq_lens, abs_tables)


def write_kv_pages(key_cache, value_cache, new_k, new_v, positions,
                   block_tables):
    """Scatter one new token's K/V per sequence into the paged cache.

    new_k/new_v: [batch, num_kv_heads, head_dim]; positions: [batch] slot
    index of the new token (0-based). Returns updated caches. The page-
    major layout keeps this a natural in-place scatter on a loop-carried
    pool: the indexed page dim leads; within the page the token's
    [n_kv, d] rows land at slot stride (head-major pages trade the r4
    contiguous token row for contiguous per-head READS — the decode
    loop reads ~100x more than it writes).
    """
    page_size = key_cache.shape[2]
    b = positions.shape[0]
    page_ids = block_tables[jnp.arange(b), positions // page_size]  # [b]
    slots = positions % page_size                                   # [b]
    key_cache = key_cache.at[page_ids, :, slots].set(
        new_k.astype(key_cache.dtype))
    value_cache = value_cache.at[page_ids, :, slots].set(
        new_v.astype(value_cache.dtype))
    return key_cache, value_cache


def write_prefill_kv_pages(key_cache, value_cache, k, v, block_tables,
                           start=None, valid_lens=None):
    """Write a prompt chunk's K/V ([batch, seq, n_kv, d]) into pages.

    ``start`` (optional [batch] int32): per-sequence position offset —
    the chunked-prefill path writes chunk c's tokens at positions
    ``start .. start+seq-1`` (default: position 0, fresh sequences).
    ``valid_lens`` (optional [batch] int32): rows ``>= valid_lens[b]``
    are PADDING — their writes are routed to page 0 (the reserved
    scratch page) so a right-padded final chunk never clobbers live
    pages past the table's real coverage.
    ``key_cache``/``value_cache`` may be quantized (int8 rows, f32
    scale plane) tuples — rows are then int8-quantized per (token,
    head) on the way in (the cache-KV int8 serving mode).
    """
    b, s, n_kv, d = k.shape
    quant = isinstance(key_cache, tuple)
    page_size = (key_cache[0] if quant else key_cache).shape[2]
    if start is None:
        pos = jnp.arange(s)
        page_ids = block_tables[:, pos // page_size]      # [b, s]
        slots = jnp.broadcast_to(pos % page_size, (b, s))  # [b, s]
    else:
        pos2 = start.astype(jnp.int32)[:, None] \
            + jnp.arange(s, dtype=jnp.int32)[None, :]      # [b, s]
        # clamp the page INDEX into the table width (pad rows may point
        # past it); the scratch reroute below keeps clamped rows dead
        pidx = jnp.minimum(pos2 // page_size,
                           block_tables.shape[1] - 1)
        page_ids = jnp.take_along_axis(block_tables, pidx, axis=1)
        slots = pos2 % page_size
    if valid_lens is not None:
        valid = jnp.arange(s, dtype=jnp.int32)[None, :] \
            < valid_lens.astype(jnp.int32)[:, None]        # [b, s]
        page_ids = jnp.where(valid, page_ids, 0)
        slots = jnp.where(valid, slots, 0)
    if quant:
        kq_pool, ks_plane = key_cache
        vq_pool, vs_plane = value_cache
        cols = (page_ids * page_size + slots).reshape(-1)   # [b*s]
        qk, sk = quantize_kv_rows(k)
        qv, sv = quantize_kv_rows(v)
        kq_pool = kq_pool.at[page_ids, :, slots].set(qk)
        vq_pool = vq_pool.at[page_ids, :, slots].set(qv)
        ks_plane = ks_plane.at[:, cols].set(
            jnp.moveaxis(sk.reshape(b * s, n_kv), 0, 1))
        vs_plane = vs_plane.at[:, cols].set(
            jnp.moveaxis(sv.reshape(b * s, n_kv), 0, 1))
        return (kq_pool, ks_plane), (vq_pool, vs_plane)
    key_cache = key_cache.at[page_ids, :, slots].set(
        k.astype(key_cache.dtype))
    value_cache = value_cache.at[page_ids, :, slots].set(
        v.astype(value_cache.dtype))
    return key_cache, value_cache


def gather_kv_pages(cache_side, block_tables, out_dtype=None):
    """Gather one cache side's pages into token-major [b, S, n_kv, d]
    (S = table_width * page_size, token t = page t//ps, slot t%ps).
    LEGACY chunked-prefill K/V view: since ISSUE 13 the default prefill
    attend reads the pool IN PLACE through
    ``flash_varlen.paged_prefill_attention`` (this dense copy cost an
    extra O(S) HBM write+read per chunk per layer); this gather remains
    the int8-quantized-pool path (it dequantizes on the way out) and
    the ``FLAGS_prefill_attention_backend=gather`` reference. Callers
    mask dead positions by seq_lens/causality, so garbage rows are
    harmless. ``block_tables`` must hold ABSOLUTE (layer-offset) page
    ids."""
    quant = isinstance(cache_side, tuple)
    pool = cache_side[0] if quant else cache_side
    b, P = block_tables.shape
    _, n_kv, ps, d = pool.shape
    g = pool[block_tables]                       # [b, P, n_kv, ps, d]
    g = jnp.moveaxis(g, 2, 3).reshape(b, P * ps, n_kv, d)
    if quant:
        plane = cache_side[1]                    # [n_kv, pool_tokens]
        cols = (block_tables[:, :, None] * ps
                + jnp.arange(ps, dtype=jnp.int32)[None, None, :]) \
            .reshape(b, P * ps)                  # [b, S]
        scales = jnp.moveaxis(plane[:, cols], 0, -1)   # [b, S, n_kv]
        g = g.astype(jnp.float32) * scales[..., None]
    return g if out_dtype is None else g.astype(out_dtype)


def quantize_kv_rows(x):
    """Per-(row..., head) symmetric int8 quantization of K/V token rows
    x [..., n_kv, d] -> (q int8 [..., n_kv, d], scale f32 [..., n_kv]).
    The serving cache-KV quantizer (reference comparator: the
    cache_k/v_quant_scales operands of block_multi_head_attention,
    paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, 1e-8)
    qv = jnp.clip(jnp.round(xf / s[..., None]), -127, 127) \
        .astype(jnp.int8)
    return qv, s


def paged_decode_attention_inplace_q(q, new_k, new_v, kq_pool, ks_plane,
                                     vq_pool, vs_plane, seq_lens,
                                     block_tables, pool_base=None,
                                     pool_pages=None, ownership=None):
    """int8-KV variant of ``paged_decode_attention_inplace``.

    The KV cache holds int8 token rows (same head-major page layout)
    plus per-token-per-head f32 scales kept as LANE-MAJOR planes
    [n_kv, total_tokens] so the kernel can apply them as logits-COLUMN
    multiplies — the only layout in which dequant costs O(b*C) VPU ops
    instead of O(C*d) per chunk (a per-element dequant of the streamed
    data measured ~2.7ms/step of pure VPU, erasing the DMA saving).
    All matmuls run on the int8 MXU path (2x bf16 rate):
      logits = (qq @ kq^T) * q_scale[row] * k_scale[col]
      pv     = (wq @ vq)   * w_scale[row],  w' = softmax_w * v_scale[col]
    with q and the softmax weights quantized per-row on the fly. The
    current token joins unquantized from operands (exact); its K/V rows
    are RMW-patched into the int8 pages and its scales blended into the
    scale planes (which ride through the kernel as blocked aliased
    outputs — they never touch a non-Pallas op in the decode loop).

    Halves attention HBM traffic vs bf16 KV. Opt-in via the engine's
    ``kv_dtype="int8"``. Reference comparator: cache-KV int8 serving
    (block_multi_head_attention cache_*_quant_scales).

    Same ``seq_lens < pages_per_seq*page_size`` precondition as
    ``paged_decode_attention_inplace``: overfull rows take a masked
    no-op write (zeroed page-slot selector, scale-plane patch dropped)
    instead of corrupting their last allocated page.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n_q, d = q.shape
    _, n_kv, ps, _ = kq_pool.shape
    P = int(pool_pages) if pool_pages is not None else kq_pool.shape[0]
    g = n_q // n_kv
    bg = b * g
    scale = d ** -0.5
    NEG = -1e30

    cp = _pick_chunk_pages(P, ps)
    C = cp * ps
    nchunks = P // cp
    T = P * ps           # tokens per layer region
    rows_pp = n_kv * ps  # pool rows per page (flattened int8 view)

    if ownership is None:
        ownership = build_pool_ownership(block_tables, seq_lens, P, ps)
    owner_tok, pos_tok = ownership
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    valid_full = ((owner_tok[None, :] == rows)
                  & (pos_tok[None, :]
                     < seq_lens.astype(jnp.int32)[:, None]))
    mask3 = jnp.transpose(
        valid_full.astype(jnp.int32).reshape(b, nchunks, C), (1, 0, 2))

    # q -> int8 rows + scales in the kernel's [n_kv, bg, ...] layout
    qt = jnp.transpose(q.reshape(b, n_kv, g, d), (1, 0, 2, 3)) \
        .reshape(n_kv, bg, d)
    qq, qs = quantize_kv_rows(
        jnp.swapaxes(qt, 0, 1).reshape(bg, n_kv, d))   # [bg,n_kv,..]
    qq = jnp.swapaxes(qq, 0, 1)                        # [n_kv, bg, d]
    qs = jnp.swapaxes(qs, 0, 1)                        # [n_kv, bg]
    nk_t = jnp.swapaxes(new_k, 0, 1).astype(jnp.bfloat16)
    nv_t = jnp.swapaxes(new_v, 0, 1).astype(jnp.bfloat16)

    # quantized current rows for the page patch + plane blend values
    nkq, nks = quantize_kv_rows(new_k)                 # [b,n_kv,d],[b,n_kv]
    nvq, nvs = quantize_kv_rows(new_v)
    nkq_w = jnp.broadcast_to(nkq[:, :, None, :], (b, n_kv, ps, d)) \
        .reshape(b, rows_pp, d)
    nvq_w = jnp.broadcast_to(nvq[:, :, None, :], (b, n_kv, ps, d)) \
        .reshape(b, rows_pp, d)

    base = jnp.asarray(0 if pool_base is None else pool_base, jnp.int32)
    lens_i = seq_lens.astype(jnp.int32)
    # overfull-row guard (see docstring): clamp the page index, zero the
    # slot selector, and push the scale-plane patch token out of range
    # so its scatter drops — masked no-op write all the way down
    pp = block_tables.shape[1]
    overfull = lens_i >= jnp.int32(pp * ps)                # [b]
    wpage_local = jnp.take_along_axis(
        block_tables.astype(jnp.int32),
        jnp.minimum(lens_i // ps, pp - 1)[:, None], axis=1)[:, 0]
    wpages = wpage_local + base                            # [b] abs page
    # flat row selector for the int8 page patch: [b, n_kv*ps, 1] f32
    slot_sel = ((jnp.arange(ps, dtype=jnp.int32)[None, :]
                 == (lens_i % ps)[:, None])
                & ~overfull[:, None]).astype(jnp.float32)
    sel_flat = jnp.broadcast_to(slot_sel[:, None, :], (b, n_kv, ps)) \
        .reshape(b, rows_pp)[..., None]                    # [b,rp,1]

    # scale-plane patch operands (LAYER-LOCAL token space [T]):
    # one-hot columns at each row's write position + the new values
    wtok = jnp.where(overfull, jnp.int32(T),
                     wpage_local * ps + lens_i % ps)       # [b] 0..T
    sel_col = jnp.zeros((1, T), jnp.float32).at[0, wtok].set(
        1.0, mode="drop")
    kval = jnp.zeros((n_kv, T), jnp.float32).at[:, wtok].set(
        jnp.swapaxes(nks, 0, 1), mode="drop")
    vval = jnp.zeros((n_kv, T), jnp.float32).at[:, wtok].set(
        jnp.swapaxes(nvs, 0, 1), mode="drop")

    scalars = jnp.concatenate(
        [jnp.reshape(base // jnp.int32(cp), (1,)),
         jnp.reshape((base * ps) // jnp.int32(C), (1,)), wpages])

    kq_flat = kq_pool.reshape(kq_pool.shape[0], rows_pp, d)
    vq_flat = vq_pool.reshape(vq_pool.shape[0], rows_pp, d)

    def kernel(s_ref, qq_ref, qs_ref, mask_ref, nk_ref, nv_ref,
               nkq_ref, nvq_ref, self_ref, selc_ref, kval_ref, vval_ref,
               ks_ref, vs_ref, kq_hbm_in, vq_hbm_in,
               o_ref, kq_hbm, vq_hbm, kso_ref, vso_ref,
               kb, vb, pgq, pgv, m_ref, l_ref, acc_ref,
               rsem, pin_sem, pout_sem):
        c = pl.program_id(0)
        base_c = s_ref[0]

        def chunk_copy(idx, slot):
            return (
                pltpu.make_async_copy(
                    kq_hbm.at[pl.ds((base_c + idx) * cp, cp)],
                    kb.at[slot], rsem.at[slot, 0]),
                pltpu.make_async_copy(
                    vq_hbm.at[pl.ds((base_c + idx) * cp, cp)],
                    vb.at[slot], rsem.at[slot, 1]))

        def page_in(i):
            pid = s_ref[2 + i]
            return (
                pltpu.make_async_copy(kq_hbm.at[pid], pgq.at[i],
                                      pin_sem.at[i, 0]),
                pltpu.make_async_copy(vq_hbm.at[pid], pgv.at[i],
                                      pin_sem.at[i, 1]))

        def page_out(i):
            pid = s_ref[2 + i]
            return (
                pltpu.make_async_copy(pgq.at[i], kq_hbm.at[pid],
                                      pout_sem.at[i, 0]),
                pltpu.make_async_copy(pgv.at[i], vq_hbm.at[pid],
                                      pout_sem.at[i, 1]))

        @pl.when(c == 0)
        def _():
            m_ref[...] = jnp.full((n_kv, bg), NEG, jnp.float32)
            l_ref[...] = jnp.zeros((n_kv, bg), jnp.float32)
            acc_ref[...] = jnp.zeros((n_kv, bg, d), jnp.float32)
            for cpy in chunk_copy(jnp.int32(0), jnp.int32(0)):
                cpy.start()
            for i in range(b):
                for cpy in page_in(i):
                    cpy.start()
            for i in range(b):
                for cpy in page_in(i):
                    cpy.wait()
            sel = self_ref[...]                      # [b, rp, 1] f32
            inv = jnp.float32(1.0) - sel
            pgq[...] = (pgq[...].astype(jnp.float32) * inv
                        + nkq_ref[...].astype(jnp.float32) * sel) \
                .astype(pgq.dtype)
            pgv[...] = (pgv[...].astype(jnp.float32) * inv
                        + nvq_ref[...].astype(jnp.float32) * sel) \
                .astype(pgv.dtype)
            for i in range(b):
                for cpy in page_out(i):
                    cpy.start()

        @pl.when(c + 1 < nchunks)
        def _():
            for cpy in chunk_copy(c + 1, jax.lax.rem(c + 1,
                                                     jnp.int32(2))):
                cpy.start()

        slot = jax.lax.rem(c, jnp.int32(2))
        for cpy in chunk_copy(c, slot):
            cpy.wait()

        # scale planes: blend in the current tokens' scales, expose the
        # blended block for this chunk, write it back (aliased output)
        selc = selc_ref[...]                         # [1, C]
        ks_blend = ks_ref[...] * (jnp.float32(1.0) - selc) \
            + kval_ref[...] * selc                   # [n_kv, C]
        vs_blend = vs_ref[...] * (jnp.float32(1.0) - selc) \
            + vval_ref[...] * selc
        kso_ref[...] = ks_blend
        vso_ref[...] = vs_blend

        valid = mask_ref[0] != 0                     # [b, C]
        if g > 1:
            valid = jnp.repeat(valid, g, axis=0)     # [bg, C]
        diag = (jax.lax.broadcasted_iota(jnp.int32, (bg, b), 0) // g
                == jax.lax.broadcasted_iota(jnp.int32, (bg, b), 1))

        for h in range(n_kv):
            k_h = kb[slot][:, h * ps:(h + 1) * ps].reshape(C, d)
            v_h = vb[slot][:, h * ps:(h + 1) * ps].reshape(C, d)
            li = jax.lax.dot_general(
                qq_ref[h], k_h, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.int32)     # [bg, C] int32
            logits = (li.astype(jnp.float32)
                      * (qs_ref[h] * jnp.float32(scale))[:, None]
                      * ks_blend[h][None, :])
            logits = jnp.where(valid, logits, jnp.float32(NEG))
            m = m_ref[h]
            pm = jnp.maximum(m, logits.max(-1))
            alpha = jnp.exp(m - pm)
            w = jnp.exp(logits - pm[:, None])
            w = jnp.where(valid, w, jnp.float32(0.0))
            l_h = l_ref[h] * alpha + w.sum(-1)
            # fold the V column scales into w, re-quantize per row
            wv = w * vs_blend[h][None, :]
            ws = jnp.maximum(wv.max(-1), jnp.float32(1e-20)) \
                / jnp.float32(127.0)                  # [bg]
            wq = jnp.clip(jnp.round(wv / ws[:, None]),
                          -127, 127).astype(jnp.int8)
            pvi = jax.lax.dot_general(
                wq, v_h, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.int32)     # [bg, d]
            pv = pvi.astype(jnp.float32) * ws[:, None]
            acc_ref[h] = acc_ref[h] * alpha[:, None] + pv
            m_ref[h] = pm
            l_ref[h] = l_h

        @pl.when(c == nchunks - 1)
        def _():
            # current token, exact bf16 operands
            for h in range(n_kv):
                qf = (qq_ref[h].astype(jnp.float32)
                      * qs_ref[h][:, None]).astype(jnp.bfloat16)
                lc = jax.lax.dot_general(
                    qf, nk_ref[h], (((1,), (1,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32) \
                    * jnp.float32(scale)
                lc = jnp.where(diag, lc, jnp.float32(NEG))
                m = m_ref[h]
                pm = jnp.maximum(m, lc.max(-1))
                alpha = jnp.exp(m - pm)
                wc = jnp.exp(lc - pm[:, None])
                wc = jnp.where(diag, wc, jnp.float32(0.0))
                l_h = l_ref[h] * alpha + wc.sum(-1)
                pv = jax.lax.dot_general(
                    wc.astype(jnp.bfloat16), nv_ref[h],
                    (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32)
                acc_h = acc_ref[h] * alpha[:, None] + pv
                o_ref[h] = acc_h / jnp.maximum(
                    l_h, jnp.float32(1e-30))[:, None]
            for i in range(b):
                for cpy in page_out(i):
                    cpy.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((n_kv, bg, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((n_kv, bg), lambda c, s: (0, 0)),
            pl.BlockSpec((1, b, C), lambda c, s: (c, 0, 0)),
            pl.BlockSpec((n_kv, b, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((n_kv, b, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((b, rows_pp, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((b, rows_pp, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec((b, rows_pp, 1), lambda c, s: (0, 0, 0)),
            # sel/val patch operands are LAYER-LOCAL [.., T] -> block c;
            # the scale PLANES span all layers -> block s[1] + c
            pl.BlockSpec((1, C), lambda c, s: (0, c)),
            pl.BlockSpec((n_kv, C), lambda c, s: (0, c)),
            pl.BlockSpec((n_kv, C), lambda c, s: (0, c)),
            pl.BlockSpec((n_kv, C), lambda c, s: (0, s[1] + c)),
            pl.BlockSpec((n_kv, C), lambda c, s: (0, s[1] + c)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
        ],
        out_specs=[
            pl.BlockSpec((n_kv, bg, d), lambda c, s: (0, 0, 0)),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec(memory_space=_pltpu_memspace(pltpu).ANY),
            pl.BlockSpec((n_kv, C), lambda c, s: (0, s[1] + c)),
            pl.BlockSpec((n_kv, C), lambda c, s: (0, s[1] + c)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, cp, rows_pp, d), jnp.int8),
            pltpu.VMEM((2, cp, rows_pp, d), jnp.int8),
            pltpu.VMEM((b, rows_pp, d), jnp.int8),
            pltpu.VMEM((b, rows_pp, d), jnp.int8),
            pltpu.VMEM((n_kv, bg), jnp.float32),
            pltpu.VMEM((n_kv, bg), jnp.float32),
            pltpu.VMEM((n_kv, bg, d), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((b, 2)),
            pltpu.SemaphoreType.DMA((b, 2)),
        ])
    with _enable_x64(False):
        out, kq2, vq2, ks2, vs2 = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((n_kv, bg, d), jnp.float32),
                jax.ShapeDtypeStruct(kq_flat.shape, jnp.int8),
                jax.ShapeDtypeStruct(vq_flat.shape, jnp.int8),
                jax.ShapeDtypeStruct(ks_plane.shape, jnp.float32),
                jax.ShapeDtypeStruct(vs_plane.shape, jnp.float32),
            ],
            # inputs numbered with the scalar operand as 0: kq=14,
            # vq=15, ks=13? -> see in_specs order: [qq1, qs2, mask3,
            # nk4, nv5, nkq6, nvq7, self8, selc9, kval10, vval11,
            # ks12, vs13, kq14, vq15]
            input_output_aliases={14: 1, 15: 2, 12: 3, 13: 4},
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=not _on_tpu(),
        )(scalars, qq, qs, mask3, nk_t, nv_t, nkq_w, nvq_w, sel_flat,
          sel_col, kval, vval, ks_plane, vs_plane, kq_flat, vq_flat)
    out = jnp.transpose(out.reshape(n_kv, b, g, d), (1, 0, 2, 3))
    return (out.reshape(b, n_q, d).astype(q.dtype),
            kq2.reshape(kq_pool.shape), ks2,
            vq2.reshape(vq_pool.shape), vs2)
