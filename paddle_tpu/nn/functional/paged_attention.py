"""Paged-KV block attention — the serving attention path.

TPU-native equivalent of the reference's paged-KV serving kernel
(reference: paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
and the decode kernel family masked_multihead_attention_kernel.cu). The KV
cache lives in fixed-size pages addressed through per-sequence block
tables, so sequences grow without reallocation/copy and memory is shared
across a continuous batch.

On TPU the hot path is the Pallas paged-attention kernel
(jax.experimental.pallas.ops.tpu.paged_attention — MXU-tiled online
softmax reading pages straight from HBM); elsewhere an XLA gather +
masked dense attention computes the same thing (fake-device test
precedent, SURVEY §4).

Layouts (match the Pallas kernel):
  q            [batch, num_q_heads, head_dim]        one decode token/seq
  key_cache    [num_kv_heads, num_pages, page_size, head_dim]
  value_cache  [num_kv_heads, num_pages, page_size, head_dim]
  seq_lens     [batch] int32   tokens already in cache (incl. current)
  block_tables [batch, pages_per_seq] int32          page ids per sequence
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["paged_attention", "write_kv_pages", "write_prefill_kv_pages"]


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pallas_paged(q, key_cache, value_cache, seq_lens, block_tables):
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention as kernel,
    )

    page_size = key_cache.shape[2]
    pages_per_seq = block_tables.shape[1]
    # one compute block ≥ 512 tokens of K keeps the MXU fed
    ppcb = max(1, min(pages_per_seq, 512 // max(page_size, 1)))
    while pages_per_seq % ppcb:
        ppcb -= 1
    # the kernel computes raw q·k logits — fold the 1/sqrt(d) scale into q
    out_dtype = q.dtype
    q = q.astype(jnp.float32) * (q.shape[-1] ** -0.5)
    with jax.enable_x64(False), jax.default_matmul_precision("default"):
        return kernel(
            q, key_cache, value_cache,
            seq_lens.astype(jnp.int32), block_tables.astype(jnp.int32),
            pages_per_compute_block=ppcb,
        ).astype(out_dtype)


def _xla_paged(q, key_cache, value_cache, seq_lens, block_tables):
    b, n_q, d = q.shape
    n_kv, _, page_size, _ = key_cache.shape
    pages_per_seq = block_tables.shape[1]
    max_len = pages_per_seq * page_size

    # gather pages: [n_kv, b, pages, page, d] -> [b, n_kv, max_len, d]
    k = key_cache[:, block_tables]
    v = value_cache[:, block_tables]
    k = jnp.transpose(k, (1, 0, 2, 3, 4)).reshape(b, n_kv, max_len, d)
    v = jnp.transpose(v, (1, 0, 2, 3, 4)).reshape(b, n_kv, max_len, d)

    group = n_q // n_kv  # GQA: q heads per kv head
    qh = q.reshape(b, n_kv, group, d)
    logits = jnp.einsum("bngd,bnkd->bngk", qh.astype(jnp.float32),
                        k.astype(jnp.float32)) * (d ** -0.5)
    pos = jnp.arange(max_len)
    mask = pos[None, :] < seq_lens[:, None]           # [b, max_len]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bngk,bnkd->bngd", w, v.astype(jnp.float32))
    return out.reshape(b, n_q, d).astype(q.dtype)


def paged_attention(q, key_cache, value_cache, seq_lens, block_tables):
    """Single-token decode attention over a paged KV cache.

    Raw-array functional op (used inside compiled decode steps).

    Backend selection (FLAGS_paged_attention_backend: auto|xla|pallas):
    ``auto`` uses the XLA gather+masked-attention path on TPU. Measured
    reason (r4, 1.3B decode): the stock Pallas kernel imposes the
    default ``{3,2,1,0}`` layout on the cache operands while the
    in-place page scatter prefers ``{3,0,2,1}``, so mixing them makes
    XLA insert two full-pool layout copies per layer per token —
    catastrophically slower than the gather it avoids. All-XLA keeps
    one layout end-to-end. The Pallas kernel stays available for
    layouts/configs where it wins (requires head_dim % 128 == 0).
    """
    from ...core.flags import flag

    backend = flag("paged_attention_backend")
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"FLAGS_paged_attention_backend={backend!r}: valid values "
            "are 'auto', 'xla', 'pallas'")
    if backend == "pallas":
        return _pallas_paged(q, key_cache, value_cache, seq_lens,
                             block_tables)
    return _xla_paged(q, key_cache, value_cache, seq_lens, block_tables)


def write_kv_pages(key_cache, value_cache, new_k, new_v, positions,
                   block_tables):
    """Scatter one new token's K/V per sequence into the paged cache.

    new_k/new_v: [batch, num_kv_heads, head_dim]; positions: [batch] slot
    index of the new token (0-based). Returns updated caches. This is the
    cache-write half of the reference's block_multi_head_attention (which
    fuses append + attend); under XLA the scatter fuses into the decode
    program so the split costs nothing.
    """
    page_size = key_cache.shape[2]
    b = positions.shape[0]
    page_ids = block_tables[jnp.arange(b), positions // page_size]  # [b]
    slots = positions % page_size                                   # [b]
    # index pattern [h, b-page, b-slot] -> positions [n_kv, b, d]
    k_t = jnp.transpose(new_k, (1, 0, 2)).astype(key_cache.dtype)
    v_t = jnp.transpose(new_v, (1, 0, 2)).astype(value_cache.dtype)
    key_cache = key_cache.at[:, page_ids, slots].set(k_t)
    value_cache = value_cache.at[:, page_ids, slots].set(v_t)
    return key_cache, value_cache


def write_prefill_kv_pages(key_cache, value_cache, k, v, block_tables):
    """Write a whole prompt's K/V ([batch, seq, n_kv, d]) into pages.

    Assumes the prompt starts at position 0 (fresh sequences).
    """
    b, s, n_kv, d = k.shape
    page_size = key_cache.shape[2]
    pos = jnp.arange(s)
    page_ids = block_tables[:, pos // page_size]      # [b, s]
    slots = pos % page_size                           # [s]
    bcast_slots = jnp.broadcast_to(slots, (b, s))
    k_t = jnp.transpose(k, (2, 0, 1, 3)).astype(key_cache.dtype)
    v_t = jnp.transpose(v, (2, 0, 1, 3)).astype(value_cache.dtype)
    key_cache = key_cache.at[:, page_ids, bcast_slots].set(k_t)
    value_cache = value_cache.at[:, page_ids, bcast_slots].set(v_t)
    return key_cache, value_cache
