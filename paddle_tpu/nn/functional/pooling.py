"""Pooling functionals.

TPU-native equivalent of the reference's pooling ops (reference:
python/paddle/nn/functional/pooling.py → phi/kernels/pool_kernel.h).
Implemented with ``lax.reduce_window``, which XLA lowers to efficient
windowed reductions on TPU.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ...ops.dispatch import eager_apply, as_tensor_args
from .conv import _tuplize, _padding

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _window(n, kernel, stride, padding, ceil_mode, channel_last):
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pad = _padding(padding, n)
    if isinstance(pad, str):
        raise ValueError("string padding not supported for pooling yet")
    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = [(0, 0)] + pad + [(0, 0)]
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = [(0, 0), (0, 0)] + pad
    if ceil_mode:
        pads = [
            (lo, hi + (s - 1)) if d > 1 else (lo, hi)
            for (lo, hi), s, d in zip(pads, strides, dims)
        ]
    return dims, strides, pads


def _pool_nd(n, kind, x, kernel_size, stride, padding, ceil_mode,
             exclusive, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    dims, strides, pads = _window(n, kernel_size, stride, padding, ceil_mode,
                                  channel_last)

    def raw(a):
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            return lax.reduce_window(a, init, lax.max, dims, strides, pads)
        s = lax.reduce_window(a, 0.0, lax.add, dims, strides, pads)
        if exclusive and any(p != (0, 0) for p in pads):
            ones = jnp.ones(a.shape, a.dtype)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
            return s / cnt
        return s / float(np.prod([d for d in dims if d > 1]))

    return eager_apply(f"{kind}_pool{n}d", raw, as_tensor_args(x))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(1, "avg", x, kernel_size, stride, padding, ceil_mode,
                    exclusive, "NCW")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool_nd(2, "avg", x, kernel_size, stride, padding, ceil_mode,
                    exclusive, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_nd(3, "avg", x, kernel_size, stride, padding, ceil_mode,
                    exclusive, data_format)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = _pool_nd(1, "max", x, kernel_size, stride, padding, ceil_mode,
                   True, "NCW")
    return (out, _pool_indices(1, x, out, kernel_size, stride, padding)) \
        if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(2, "max", x, kernel_size, stride, padding, ceil_mode,
                   True, data_format)
    return (out, _pool_indices(2, x, out, kernel_size, stride, padding)) \
        if return_mask else out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool_nd(3, "max", x, kernel_size, stride, padding, ceil_mode,
                   True, data_format)
    return (out, _pool_indices(3, x, out, kernel_size, stride, padding)) \
        if return_mask else out


def _pool_indices(n, x, out, kernel_size, stride, padding):
    """Flat within-window index of the max (the reference's ``return_mask``).

    Supported for zero padding; each window offset contributes one strided
    slice, argmax over the stacked offsets gives the winner's flat index.
    """
    kernel = _tuplize(kernel_size, n)
    stride_t = _tuplize(stride if stride is not None else kernel_size, n)
    if _padding(padding, n) != [(0, 0)] * n:
        raise NotImplementedError("return_mask requires padding=0")

    def raw(a):
        out_sp = out._data.shape[2:]
        patches = []
        for pos in np.ndindex(*kernel):
            slices = [slice(None), slice(None)]
            for i in range(n):
                start = pos[i]
                end = start + (out_sp[i] - 1) * stride_t[i] + 1
                slices.append(slice(start, end, stride_t[i]))
            patches.append(a[tuple(slices)])
        stacked = jnp.stack(patches, axis=0)
        return jnp.argmax(stacked, axis=0).astype(jnp.int64)

    return eager_apply("max_pool_indices", raw, as_tensor_args(x))


def _adaptive_pool(n, kind, x, output_size, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    if channel_last:
        raise NotImplementedError("adaptive pooling supports NCHW-family only")
    out_size = _tuplize(output_size, n)

    def raw(a):
        spatial = a.shape[2:]
        r = a
        for i in range(n):
            axis = 2 + i
            in_s, out_s = spatial[i], out_size[i]
            if out_s is None or in_s == out_s:
                continue
            if in_s % out_s == 0:
                k = in_s // out_s
                new_shape = r.shape[:axis] + (out_s, k) + r.shape[axis + 1:]
                rr = r.reshape(new_shape)
                r = jnp.max(rr, axis=axis + 1) if kind == "max" else \
                    jnp.mean(rr, axis=axis + 1)
            else:
                # general case: per-output-bin variable windows
                starts = np.floor(np.arange(out_s) * in_s / out_s).astype(int)
                ends = np.ceil((np.arange(out_s) + 1) * in_s / out_s).astype(int)
                pieces = []
                for s, e in zip(starts, ends):
                    seg = lax.slice_in_dim(r, s, e, axis=axis)
                    red = jnp.max(seg, axis=axis, keepdims=True) if kind == "max" \
                        else jnp.mean(seg, axis=axis, keepdims=True)
                    pieces.append(red)
                r = jnp.concatenate(pieces, axis=axis)
        return r

    return eager_apply(f"adaptive_{kind}_pool{n}d", raw, as_tensor_args(x))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(1, "avg", x, output_size, "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(2, "avg", x, output_size, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(3, "avg", x, output_size, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(1, "max", x, output_size, "NCW")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(2, "max", x, output_size, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(3, "max", x, output_size, "NCDHW")


def _max_unpool(n, x, indices, kernel_size, stride, padding, output_size):
    """Inverse of max_pool with return_mask (ops.yaml unpool/unpool3d):
    scatters each pooled value back to its winning position. ``indices``
    are this framework's within-window offsets (what return_mask
    produces), so pool -> unpool roundtrips exactly."""
    kernel = _tuplize(kernel_size, n)
    stride_t = _tuplize(stride if stride is not None else kernel_size, n)
    if _padding(padding, n) != [(0, 0)] * n:
        raise NotImplementedError("max_unpool requires padding=0")

    def raw(a, idx):
        sp_in = a.shape[2:]
        if output_size is not None:
            sp_out = tuple(output_size)[-n:]
        else:
            sp_out = tuple((sp_in[i] - 1) * stride_t[i] + kernel[i]
                           for i in range(n))
        acc = jnp.full(a.shape[:2] + sp_out, -jnp.inf, a.dtype)
        for k, pos in enumerate(np.ndindex(*kernel)):
            contrib = jnp.where(idx == k, a, -jnp.inf)
            slices = [slice(None), slice(None)]
            for i in range(n):
                start = pos[i]
                end = start + (sp_in[i] - 1) * stride_t[i] + 1
                slices.append(slice(start, end, stride_t[i]))
            acc = acc.at[tuple(slices)].max(contrib)
        return jnp.where(jnp.isneginf(acc), 0.0, acc)

    return eager_apply("max_unpool", raw, as_tensor_args(x, indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(1, x, indices, kernel_size, stride, padding,
                       output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """(ops.yaml unpool)"""
    return _max_unpool(2, x, indices, kernel_size, stride, padding,
                       output_size)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """(ops.yaml unpool3d)"""
    return _max_unpool(3, x, indices, kernel_size, stride, padding,
                       output_size)
