"""Ring attention: attention-level sequence/context parallelism.

The reference scales sequence length with Megatron-SP + a `sep` mesh axis
+ FlashAttention (SURVEY.md §5.7) but has NO ring attention; this module
covers that surface the TPU-native way, as §5.7 prescribes: q/k/v sharded
on the sequence dim over a mesh axis, K/V blocks rotated around the ring
with ``lax.ppermute`` (ICI neighbor exchange), online-softmax
rescaling accumulates exact attention — memory per device is O(seq/N),
and the ppermute overlaps with the block matmuls.

Layout: [batch, seqlen, heads, head_dim] (paddle flash_attention layout).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor
from ...ops.dispatch import as_tensor_args, eager_apply

__all__ = ["ring_attention", "ring_flash_attention"]


def _shard_map():
    """shard_map across jax versions (jax >= 0.7 promotes it out of
    experimental; 0.4.x only has the experimental home)."""
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    return sm


def _mark_varying(t, axis_name):
    """lax.pcast(..., to='varying') where available (newer jax tracks
    per-axis replication); on jax without pcast the shard_map below runs
    with check_rep=False, so the marking is a no-op."""
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return t
    return pcast(t, (axis_name,), to="varying")


def _ring_attention_sharded(q, k, v, axis_name: str, causal: bool,
                            scale: float, axis_size: int):
    """Per-device body under shard_map: q,k,v are local seq blocks."""
    b, sq, h, dh = q.shape
    my = lax.axis_index(axis_name)

    def block_attn(q_blk, k_blk, v_blk, q_off, k_off):
        # returns unnormalized (out, row_sum, row_max) with online softmax
        logits = jnp.einsum("bqhd,bkhd->bhqk", q_blk, k_blk) * scale
        if causal:
            sq_, sk_ = logits.shape[-2], logits.shape[-1]
            q_pos = q_off + jnp.arange(sq_)
            k_pos = k_off + jnp.arange(sk_)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -jnp.inf)
        m = jnp.max(logits, -1)                       # [b,h,q]
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        l = jnp.sum(p, -1)                            # [b,h,q]
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
        return o, l, m_safe, jnp.isfinite(m)

    sk = k.shape[1]
    q_off = my * sq

    def step(carry, i):
        o_acc, l_acc, m_acc, k_cur, v_cur = carry
        src = (my - i) % axis_size          # which rank's kv block we hold
        k_off = src * sk
        o_b, l_b, m_b, valid = block_attn(q, k_cur, v_cur, q_off, k_off)
        # online softmax merge
        m_new = jnp.maximum(m_acc, jnp.where(valid, m_b, -jnp.inf))
        m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_acc), m_acc, -jnp.inf)
                        - m_new_safe)
        alpha = jnp.where(jnp.isfinite(m_acc), alpha, 0.0)
        beta = jnp.exp(jnp.where(valid, m_b, -jnp.inf) - m_new_safe)
        beta = jnp.where(valid, beta, 0.0)
        o_acc = o_acc * alpha.transpose(0, 2, 1)[..., None] \
            + o_b * beta.transpose(0, 2, 1)[..., None]
        l_acc = l_acc * alpha + l_b * beta
        m_acc = m_new
        # rotate kv around the ring (ICI neighbor exchange)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, l_acc, m_acc, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, sq, h, dh), q.dtype)
    l0 = jnp.zeros((b, h, sq), q.dtype)
    m0 = jnp.full((b, h, sq), -jnp.inf, q.dtype)
    # carries become device-varying after step 1 (they depend on
    # axis_index); mark the inits as varying over the ring axis
    o0, l0, m0 = (_mark_varying(t, axis_name) for t in (o0, l0, m0))
    (o, l, m, _, _), _ = lax.scan(step, (o0, l0, m0, k, v),
                                  jnp.arange(axis_size))
    l_safe = jnp.maximum(l, 1e-20)
    return o / l_safe.transpose(0, 2, 1)[..., None]


def ring_attention(q, k, v, mesh=None, seq_axis: str = "sep",
                   causal: bool = False, scale: Optional[float] = None,
                   name=None):
    """Exact attention over sequence-sharded q/k/v.

    ``mesh``: a ProcessMesh containing ``seq_axis``; defaults to the fleet
    hybrid mesh. Inputs may be dist tensors sharded on dim 1 over
    ``seq_axis`` (or dense, in which case they're sharded here). Output is
    sharded the same way.
    """
    shard_map = _shard_map()

    from ...distributed.auto_parallel.placement import (
        ProcessMesh, Replicate, Shard,
    )

    if mesh is None:
        if isinstance(q, Tensor) and q._dist_attr is not None:
            mesh = q._dist_attr[0]
        else:
            from ...distributed.fleet import fleet

            mesh = fleet.get_hybrid_communicate_group().mesh
    axis_size = mesh.get_dim_size(seq_axis)
    head_dim = (q.shape if isinstance(q, Tensor) else q.shape)[-1]
    scale = scale if scale is not None else head_dim ** -0.5

    spec: list = [None, None, None, None]
    spec[1] = seq_axis
    pspec = PartitionSpec(*spec)
    jmesh = mesh.jax_mesh()

    body = functools.partial(_ring_attention_sharded, axis_name=seq_axis,
                             causal=causal, scale=scale,
                             axis_size=axis_size)
    kwargs = {}
    if getattr(lax, "pcast", None) is None:
        # no pcast -> no way to mark the scan carries device-varying, so
        # replication checking must be off (jax 0.4.x)
        kwargs["check_rep"] = False
    fn = shard_map(body, mesh=jmesh, in_specs=(pspec, pspec, pspec),
                   out_specs=pspec, **kwargs)
    jit_fn = jax.jit(fn)

    placements = [Replicate()] * mesh.ndim
    placements[mesh.dim_names.index(seq_axis)] = Shard(1)
    sharding = mesh.sharding_for(placements, 4)

    def raw(qa, ka, va):
        qa = lax.with_sharding_constraint(qa, sharding) \
            if qa.shape[1] % axis_size == 0 else qa
        return jit_fn(qa, ka, va)

    tensors = as_tensor_args(q, k, v)
    # place inputs
    for t in tensors:
        if t._dist_attr is None:
            t._data = jax.device_put(t._data, sharding)
            t._dist_attr = (mesh, placements)
    out = eager_apply("ring_attention", raw, tensors)
    out._dist_attr = (mesh, placements)
    return out


def ring_flash_attention(q, k, v, mesh=None, seq_axis="sep", causal=False,
                         dropout=0.0, training=True, name=None):
    """flash_attention-shaped wrapper (returns (out, None))."""
    out = ring_attention(q, k, v, mesh=mesh, seq_axis=seq_axis,
                         causal=causal)
    return out, None
