"""Weight-streaming linear for skinny (decode-shaped) matmuls.

The serving decode step multiplies tiny activations [batch<=64, K]
against huge weights [K, N]. XLA's dot on these shapes reaches only
~27% of v5e HBM bandwidth (tools/decode_profile.py weights_only_b32:
10.9ms/step vs the 2.9ms weight-read floor for the 1.3B stack, r5) —
the weight-tile pipeline stalls on small M. This kernel instead streams
W in multi-MB column blocks through a Pallas grid (auto double-buffered
BlockSpec DMA, the same structure that put the r5 paged-attention
kernel at ~HBM peak) and does one [M, K] x [K, bn] MXU dot per block,
with bias add, int8 weight dequant (per-output-channel scales applied
on the dot output) and the activation fused in-kernel.

Stacked-layer aware: W may be [L, K, N] with a TRACED layer index —
the block index map reads the layer from scalar prefetch, so the
decode loop never materializes a per-layer weight slice (a
dynamic-slice operand to a custom call would copy the whole layer).

Reference comparator: the fused weight-only GEMV/GEMM serving kernels
(paddle/phi/kernels/fusion/gpu/fused_weight_only_linear_pass &
masked_multihead_attention's surrounding fused_multi_transformer step).

A8W8 mode (``act_quant=True``): activations are dynamically quantized
per token (absmax -> int8 + fp32 scale, quantization/dynamic.py) ahead
of the GEMM, the kernel computes the [M, K] x [K, bn] dot int8 x int8
with **int32 MXU accumulation**, and the accumulator is dequantized
ONCE with ``act_scale (x) per-output-channel weight_scale`` (bias added
post-dequant). This removes the int8->bf16 weight convert from the
streamed read AND keeps the skinny matmul's math on the int8 MXU —
the missing half of the reference's full-int8 serving matmuls
(fused_multi_transformer_int8_op.cu quantize/dequant rounds around its
int8 GEMMs). Off-TPU / ragged shapes fall back to the same math via
``lax.dot_general(..., preferred_element_type=int32)``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .paged_attention import (_enable_x64, _on_tpu,
                              _pltpu_compiler_params)

__all__ = ["stream_linear"]


_TARGET_BLOCK_BYTES = 4 << 20

#: int8 VMEM tiles are (32, 128) — the quantized-activation block is
#: padded up to this sublane multiple before entering the kernel
_INT8_SUBLANES = 32


def _pick_bn(K: int, N: int, itemsize: int) -> int:
    """Largest 128-multiple divisor of N whose [K, bn] block is a few
    MB (big DMAs keep the HBM stream saturated)."""
    cap = max(128, _TARGET_BLOCK_BYTES // max(K * itemsize, 1))
    best = 0
    for bn in range(128, min(cap, N) + 1, 128):
        if N % bn == 0:
            best = bn
    return best


def _apply_activation(acc, activation):
    if activation == "gelu":
        return jax.nn.gelu(acc)
    if activation == "relu":
        return jax.nn.relu(acc)
    return acc


def _stream_linear_a8w8(x_q, x_scale, w3, s3, b3, layer, activation,
                        out_dtype, interpret=None):
    """int8-activation streaming kernel: x_q [M, K] int8 (+ per-token
    scales [M] f32) against stacked int8 weights w3 [L, K, N] with
    per-output-channel dequant scales s3 [L, 1, N] (b3 [L, 1, N] bias
    or None). One [M, K] x [K, bn] int8 MXU dot per weight block,
    int32 accumulator dequantized in-kernel by
    ``x_scale[:, None] * s3`` — the weight stream stays int8 end to
    end. Runs in Pallas interpret mode off-TPU so CPU CI pins the
    kernel's numerics (tests/test_stream_linear_a8w8.py)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x_q.shape
    N = w3.shape[-1]
    bn = _pick_bn(K, N, 1)
    if interpret is None:
        interpret = not _on_tpu()
    # pad the (tiny) activation block up to the int8 sublane tile
    Mp = -(-M // _INT8_SUBLANES) * _INT8_SUBLANES
    if Mp != M:
        x_q = jnp.pad(x_q, ((0, Mp - M), (0, 0)))
        x_scale = jnp.pad(x_scale, (0, Mp - M))
    xs2 = x_scale.reshape(Mp, 1).astype(jnp.float32)
    has_bias = b3 is not None
    nb = N // bn
    lidx = jnp.reshape(jnp.asarray(0 if layer is None else layer,
                                   jnp.int32), (1,))

    def kernel(l_ref, x_ref, xs_ref, w_ref, s_ref, *rest):
        del l_ref
        b_ref = rest[0] if has_bias else None
        o_ref = rest[-1]
        acc = jax.lax.dot_general(
            x_ref[...], w_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)          # [Mp, bn] int32
        acc = acc.astype(jnp.float32) * xs_ref[...] \
            * s_ref[0].astype(jnp.float32)
        if b_ref is not None:
            acc = acc + b_ref[0].astype(jnp.float32)
        acc = _apply_activation(acc, activation)
        o_ref[...] = acc.astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((Mp, K), lambda j, l: (0, 0)),
        pl.BlockSpec((Mp, 1), lambda j, l: (0, 0)),
        pl.BlockSpec((1, K, bn), lambda j, l: (l[0], 0, j)),
        pl.BlockSpec((1, 1, bn), lambda j, l: (l[0], 0, j)),
    ]
    operands = [x_q, xs2, w3, s3]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, bn),
                                     lambda j, l: (l[0], 0, j)))
        operands.append(b3)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((Mp, bn), lambda j, l: (0, j)),
        scratch_shapes=[])
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((Mp, N), out_dtype),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=100 * 1024 * 1024),
            interpret=interpret,
        )(lidx, *operands)
    return out[:M] if Mp != M else out


def _stream_linear_act_quant(x, w, layer, bias, scale, activation,
                             out_dtype, *, stacked):
    """A8W8 dispatch: dynamic per-token act quant, then the streaming
    int8 x int8 kernel on TPU (clean geometry) or the XLA
    ``preferred_element_type=int32`` dot everywhere else — identical
    math, so CPU serving tests exercise the same numerics the chip
    runs."""
    from ...quantization.dynamic import dynamic_act_quant

    K = x.shape[1]
    N = w.shape[-1]
    x_q, x_s = dynamic_act_quant(x)
    if _on_tpu() and _pick_bn(K, N, 1) and K % 128 == 0:
        w3 = w if stacked else w[None]
        s3 = (scale if stacked else scale[None]) \
            .reshape(w3.shape[0], 1, N).astype(jnp.float32)
        b3 = None
        if bias is not None:
            b3 = (bias if stacked else bias[None]) \
                .reshape(w3.shape[0], 1, N).astype(jnp.float32)
        return _stream_linear_a8w8(x_q, x_s, w3, s3, b3, layer,
                                   activation, out_dtype)
    from ...quantization.dynamic import int8_dot_dequant

    wl = w[layer] if stacked else w
    out = int8_dot_dequant(
        x_q, x_s, wl, (scale[layer] if stacked else scale),
        bias=(bias[layer] if stacked else bias)
        if bias is not None else None)
    return _apply_activation(out, activation).astype(out_dtype)


def stream_linear(x, w, layer=None, bias=None, scale=None,
                  activation=None, out_dtype=None, act_quant=False):
    """x [M, K] @ w[(L,) K, N] (+ bias) with streamed weights.

    layer: traced int32 index when w/bias/scale are layer-stacked.
    scale: int8 weight-only per-output-channel dequant scales [(L,) N].
    activation: None | 'gelu' | 'relu', fused on the f32 accumulator.
    act_quant: A8W8 — dynamically quantize x per token (absmax int8 +
    f32 scale) and run the GEMM int8 x int8 with int32 accumulation;
    requires int8 ``w`` with per-output-channel ``scale``.
    Returns [M, N] in out_dtype (default: x.dtype).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x.shape
    stacked = w.ndim == 3
    N = w.shape[-1]
    out_dtype = out_dtype or x.dtype
    if act_quant:
        if w.dtype != jnp.int8 or scale is None:
            raise ValueError(
                "stream_linear(act_quant=True) needs int8 weights with "
                "per-output-channel scales (quantize_weight_only_int8)")
        return _stream_linear_act_quant(
            x, w, layer, bias, scale, activation, out_dtype,
            stacked=stacked)
    bn = _pick_bn(K, N, w.dtype.itemsize)
    if bn == 0 or M % 8 != 0 or K % 128 != 0 or not _on_tpu():
        # fallback: plain XLA dot (CPU tests, odd shapes)
        wl = w[layer] if stacked else w
        out = jax.lax.dot_general(
            x, wl.astype(x.dtype) if wl.dtype == jnp.int8 else wl,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if scale is not None:
            out = out * (scale[layer] if stacked else scale)
        if bias is not None:
            out = out + (bias[layer] if stacked else bias)
        out = _apply_activation(out, activation)
        return out.astype(out_dtype)

    nb = N // bn
    has_bias = bias is not None
    has_scale = scale is not None
    # normalize operands to stacked-3D so one kernel serves both forms
    w3 = w if stacked else w[None]
    b3 = None
    s3 = None
    if has_bias:
        b3 = (bias if stacked else bias[None]).reshape(
            w3.shape[0], 1, N)
    if has_scale:
        s3 = (scale if stacked else scale[None]).reshape(
            w3.shape[0], 1, N)
    lidx = jnp.reshape(
        jnp.asarray(0 if layer is None else layer, jnp.int32), (1,))

    def kernel(l_ref, x_ref, *rest):
        del l_ref
        refs = list(rest)
        w_ref = refs.pop(0)
        b_ref = refs.pop(0) if has_bias else None
        s_ref = refs.pop(0) if has_scale else None
        o_ref = refs.pop(0)
        wb = w_ref[0]                                # [K, bn]
        acc = jax.lax.dot_general(
            x_ref[...], wb.astype(x_ref.dtype),
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)      # [M, bn]
        if s_ref is not None:
            acc = acc * s_ref[0].astype(jnp.float32)
        if b_ref is not None:
            acc = acc + b_ref[0].astype(jnp.float32)
        if activation == "gelu":
            acc = jax.nn.gelu(acc)
        elif activation == "relu":
            acc = jax.nn.relu(acc)
        o_ref[...] = acc.astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((M, K), lambda j, l: (0, 0)),
        pl.BlockSpec((1, K, bn), lambda j, l: (l[0], 0, j)),
    ]
    operands = [x, w3]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, bn), lambda j, l: (l[0], 0, j)))
        operands.append(b3)
    if has_scale:
        in_specs.insert(2 if not has_bias else 3,
                        pl.BlockSpec((1, 1, bn),
                                     lambda j, l: (l[0], 0, j)))
        operands.insert(2 if not has_bias else 3, s3)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((M, bn), lambda j, l: (0, j)),
        scratch_shapes=[])
    with _enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=100 * 1024 * 1024),
        )(lidx, *operands)
