"""Weight-streaming linears for skinny (decode-shaped) matmuls.

The serving decode step multiplies tiny activations [batch<=64, K]
against huge weights [K, N]; every step must read the full weight
stack from HBM, so decode throughput is bounded by the weight stream,
not math. What end-to-end measurement (r5, 1.3B b32) actually showed:

- int8 weights WIN through this kernel (3398 vs 3231 tok/s) because
  the int8->bf16 dequant fuses into the streamed block DMA;
- bf16 weights LOST to XLA's loop-sliced dots (2749 vs 2916 tok/s):
  per-call Pallas dispatch fixed cost + stream ramp-up paid ~6x per
  layer ate the DMA gains. (An earlier module docstring blamed "XLA
  only reaching ~27% of HBM bandwidth" on these shapes from a
  microbench — that diagnosis was debunked by the end-to-end numbers;
  the stall is per-call overhead, not XLA's tile pipeline.)

The r6 answer is structural, not a faster dot: FEWER, BIGGER,
double-buffered streams.

- ``stream_linear`` — one streamed GEMM. W streams in multi-MB
  [K, bn] column blocks through a Pallas grid (auto double-buffered
  BlockSpec DMA, the same structure that put the r5 paged-attention
  kernel at ~HBM peak), one [M, K] x [K, bn] MXU dot per block, with
  bias / int8 per-output-channel dequant / activation fused in-kernel.
  Block geometry is dtype-aware: bf16's 2-byte stream gets DOUBLE the
  column-block bytes (the DMA must be big for the 2-byte stream to
  saturate HBM) and M is padded up to the dtype's sublane tile
  (f32: 8, bf16: 16) instead of falling back to XLA on odd batches.

- ``stream_layer_tail`` — the GROUPED serving call: O-projection +
  residual + LN2 + FFN1 + activation + FFN2 + residual of one
  transformer layer as ONE streamed kernel (three weight streams in
  one grid), optionally followed by a CROSS-LAYER PREFETCH phase that
  computes layer l+1's LN1 + QKV projection from the just-finished
  hidden state — so layer l+1's first weight blocks DMA while layer
  l's FFN tail is still on the MXU, and the decode fori_loop issues
  ONE fused streamed call per layer in steady state (~2x fixed cost
  per layer instead of ~6x).

Stacked-layer aware: W may be [L, K, N] with a TRACED layer index —
the block index maps read the layer from scalar prefetch, so the
decode loop never materializes a per-layer weight slice (a
dynamic-slice operand to a custom call would copy the whole layer).

Reference comparator: the fused weight-only GEMV/GEMM serving kernels
(paddle/phi/kernels/fusion/gpu/fused_weight_only_linear_pass &
masked_multihead_attention's surrounding fused_multi_transformer step).

A8W8 mode (``act_quant=True``): activations are dynamically quantized
per token (absmax -> int8 + fp32 scale, quantization/dynamic.py) ahead
of the GEMM, the kernel computes the [M, K] x [K, bn] dot int8 x int8
with **int32 MXU accumulation**, and the accumulator is dequantized
ONCE with ``act_scale (x) per-output-channel weight_scale`` (bias added
post-dequant). This removes the int8->bf16 weight convert from the
streamed read AND keeps the skinny matmul's math on the int8 MXU —
the missing half of the reference's full-int8 serving matmuls
(fused_multi_transformer_int8_op.cu quantize/dequant rounds around its
int8 GEMMs). Off-TPU / ragged shapes fall back to the same math via
``lax.dot_general(..., preferred_element_type=int32)``. The grouped
tail accepts int8/a8w8 weight stacks too, but runs their GEMMs via
in-kernel dequant (weight-only math): the weight STREAM — the bound
resource — stays int8, only the MXU math is bf16, so ``auto`` routing
keeps full A8W8 on the ungrouped act-quant kernel.

Tensor parallelism: under the serving ``mp`` mesh (distributed/tp.py,
shard_map), every call streams a PER-SHARD slice — column-parallel
callers pass [K, N/mp] blocks (bias/scale shard along), row-parallel
callers pass [K/mp, N] with ``reduce_axis="mp"`` so the f32 partial is
psum'd before the replicated bias/activation (the collective stays
fused with the projection call). Per chip the streamed bytes are
exactly 1/mp of the stack, so TP decode keeps its weight-bandwidth
roofline per chip instead of re-streaming replicated full matrices.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...device.vmem import KERNEL_VMEM_LIMIT_BYTES
from .paged_attention import (_enable_x64, _on_tpu,
                              _pltpu_compiler_params)

__all__ = ["stream_linear", "stream_layer_tail"]


#: single-GEMM column-block byte targets per weight itemsize: big DMAs
#: keep the HBM stream saturated, and a 2-byte bf16 stream needs twice
#: the columns of an f32 one to issue the same-size DMA
_TARGET_BLOCK_BYTES = {1: 4 << 20, 2: 8 << 20, 4: 4 << 20}

#: grouped-tail per-stream byte target: the fused kernel double-buffers
#: up to four weight streams at once, so each stream gets a smaller
#: block to stay inside VMEM
_TARGET_GROUPED_BYTES = 2 << 20

#: int8 VMEM tiles are (32, 128) — the quantized-activation block is
#: padded up to this sublane multiple before entering the kernel
_INT8_SUBLANES = 32

#: f32/bf16 sublane tiles: M (the tiny batch dim) is padded up to the
#: compute dtype's tile instead of bouncing odd batches off to XLA
_SUBLANES = {4: 8, 2: 16}


def _pick_bn(K: int, N: int, itemsize: int, target=None) -> int:
    """Largest 128-multiple divisor of N whose [K, bn] block hits the
    dtype's byte target (big DMAs keep the HBM stream saturated)."""
    if target is None:
        target = _TARGET_BLOCK_BYTES.get(itemsize, 4 << 20)
    cap = max(128, target // max(K * itemsize, 1))
    best = 0
    for bn in range(128, min(cap, N) + 1, 128):
        if N % bn == 0:
            best = bn
    return best


def _sublane_pad(x):
    """Pad rows of x [M, K] up to the dtype's sublane tile; returns
    (padded, M)."""
    M = x.shape[0]
    sub = _SUBLANES.get(jnp.dtype(x.dtype).itemsize, 8)
    Mp = -(-M // sub) * sub
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    return x, M


def _apply_activation(acc, activation):
    if activation == "gelu":
        return jax.nn.gelu(acc)
    if activation == "relu":
        return jax.nn.relu(acc)
    return acc


def _ln_f32(h, scale, bias, eps):
    """f32 layer norm matching FusedMultiTransformer._ln."""
    mu = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    return (h - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def _stream_linear_a8w8(x_q, x_scale, w3, s3, b3, layer, activation,
                        out_dtype, interpret=None):
    """int8-activation streaming kernel: x_q [M, K] int8 (+ per-token
    scales [M] f32) against stacked int8 weights w3 [L, K, N] with
    per-output-channel dequant scales s3 [L, 1, N] (b3 [L, 1, N] bias
    or None). One [M, K] x [K, bn] int8 MXU dot per weight block,
    int32 accumulator dequantized in-kernel by
    ``x_scale[:, None] * s3`` — the weight stream stays int8 end to
    end. Runs in Pallas interpret mode off-TPU so CPU CI pins the
    kernel's numerics (tests/test_stream_linear_a8w8.py)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x_q.shape
    N = w3.shape[-1]
    bn = _pick_bn(K, N, 1)
    if interpret is None:
        interpret = not _on_tpu()
    # pad the (tiny) activation block up to the int8 sublane tile
    Mp = -(-M // _INT8_SUBLANES) * _INT8_SUBLANES
    if Mp != M:
        x_q = jnp.pad(x_q, ((0, Mp - M), (0, 0)))
        x_scale = jnp.pad(x_scale, (0, Mp - M))
    xs2 = x_scale.reshape(Mp, 1).astype(jnp.float32)
    has_bias = b3 is not None
    nb = N // bn
    lidx = jnp.reshape(jnp.asarray(0 if layer is None else layer,
                                   jnp.int32), (1,))

    def kernel(l_ref, x_ref, xs_ref, w_ref, s_ref, *rest):
        del l_ref
        b_ref = rest[0] if has_bias else None
        o_ref = rest[-1]
        acc = jax.lax.dot_general(
            x_ref[...], w_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)          # [Mp, bn] int32
        acc = acc.astype(jnp.float32) * xs_ref[...] \
            * s_ref[0].astype(jnp.float32)
        if b_ref is not None:
            acc = acc + b_ref[0].astype(jnp.float32)
        acc = _apply_activation(acc, activation)
        o_ref[...] = acc.astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((Mp, K), lambda j, l: (0, 0)),
        pl.BlockSpec((Mp, 1), lambda j, l: (0, 0)),
        pl.BlockSpec((1, K, bn), lambda j, l: (l[0], 0, j)),
        pl.BlockSpec((1, 1, bn), lambda j, l: (l[0], 0, j)),
    ]
    operands = [x_q, xs2, w3, s3]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, bn),
                                     lambda j, l: (l[0], 0, j)))
        operands.append(b3)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((Mp, bn), lambda j, l: (0, j)),
        scratch_shapes=[])
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((Mp, N), out_dtype),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(lidx, *operands)
    return out[:M] if Mp != M else out


def _stream_linear_act_quant(x, w, layer, bias, scale, activation,
                             out_dtype, *, stacked):
    """A8W8 dispatch: dynamic per-token act quant, then the streaming
    int8 x int8 kernel on TPU (clean geometry) or the XLA
    ``preferred_element_type=int32`` dot everywhere else — identical
    math, so CPU serving tests exercise the same numerics the chip
    runs."""
    from ...quantization.dynamic import dynamic_act_quant

    K = x.shape[1]
    N = w.shape[-1]
    x_q, x_s = dynamic_act_quant(x)
    if _on_tpu() and _pick_bn(K, N, 1) and K % 128 == 0:
        w3 = w if stacked else w[None]
        s3 = (scale if stacked else scale[None]) \
            .reshape(w3.shape[0], 1, N).astype(jnp.float32)
        b3 = None
        if bias is not None:
            b3 = (bias if stacked else bias[None]) \
                .reshape(w3.shape[0], 1, N).astype(jnp.float32)
        return _stream_linear_a8w8(x_q, x_s, w3, s3, b3, layer,
                                   activation, out_dtype)
    from ...quantization.dynamic import int8_dot_dequant

    wl = w[layer] if stacked else w
    out = int8_dot_dequant(
        x_q, x_s, wl, (scale[layer] if stacked else scale),
        bias=(bias[layer] if stacked else bias)
        if bias is not None else None)
    return _apply_activation(out, activation).astype(out_dtype)


def _ring_reduce_pipeline(x, w, layer, scale, act_quant, axis, size):
    """The ring-overlap form of the row-parallel reduction (ISSUE 19):
    the output columns split into ``size`` chunks, each chunk's GEMM
    is a SEPARATE streamed call over its weight-column slice, and
    chunk i's ``size - 1`` ppermute ring steps are emitted after chunk
    i+1's GEMM — the permutes depend only on their own chunk's
    partial, so the reduction of chunk i rides under the weight
    stream of chunk i+1 instead of waiting for the full partial.
    Returns the reduced f32 [M, N] (bias/activation stay with the
    caller, AFTER the reduction, same as the psum form)."""
    import numpy as np

    from ...distributed.tp import ring_chunk_reduce

    N = w.shape[-1]
    bounds = [int(b) for b in np.linspace(0, N, size + 1).astype(int)]
    spans = [(lo, hi) for lo, hi in zip(bounds[:-1], bounds[1:])
             if hi > lo]

    def gemm(lo, hi):
        return stream_linear(
            x, jax.lax.slice_in_dim(w, lo, hi, axis=-1), layer=layer,
            bias=None,
            scale=None if scale is None
            else jax.lax.slice_in_dim(scale, lo, hi, axis=-1),
            activation=None, out_dtype=jnp.float32,
            act_quant=act_quant)

    parts: list = []
    reduced: list = [None] * len(spans)
    for j, (lo, hi) in enumerate(spans):
        parts.append(gemm(lo, hi))
        if j >= 1:
            # ring phase for chunk j-1 under chunk j's GEMM stream
            reduced[j - 1] = ring_chunk_reduce(parts[j - 1], axis, size)
    reduced[-1] = ring_chunk_reduce(parts[-1], axis, size)
    return jnp.concatenate(reduced, axis=-1) if len(reduced) > 1 \
        else reduced[0]


def stream_linear(x, w, layer=None, bias=None, scale=None,
                  activation=None, out_dtype=None, act_quant=False,
                  reduce_axis=None, overlap=None):
    """x [M, K] @ w[(L,) K, N] (+ bias) with streamed weights.

    layer: traced int32 index when w/bias/scale are layer-stacked.
    scale: int8 weight-only per-output-channel dequant scales [(L,) N].
    activation: None | 'gelu' | 'relu', fused on the f32 accumulator.
    act_quant: A8W8 — dynamically quantize x per token (absmax int8 +
    f32 scale) and run the GEMM int8 x int8 with int32 accumulation;
    requires int8 ``w`` with per-output-channel ``scale``.
    reduce_axis: ROW-PARALLEL tensor-parallel form (inside shard_map):
    ``w`` is this shard's [K/mp, N] slice — the f32 partial product is
    reduced over the named mesh axis BEFORE the (replicated) bias add
    and activation, so the collective stays fused with the projection
    call (per-output-channel int8 dequant scales commute with the sum
    and stay per-shard, inside the streamed kernel). An axis of extent
    1 skips the collective at trace time.
    overlap: the reduction schedule when ``reduce_axis`` is set —
    ``"psum"`` (one blocking all-reduce, the bitwise/census reference)
    | ``"ring"`` (mp column chunks, each GEMM'd in its own streamed
    call and ring-reduced via ppermute under the next chunk's weight
    stream) | None (``FLAGS_tp_overlap``).
    Returns [M, N] in out_dtype (default: x.dtype).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x.shape
    stacked = w.ndim == 3
    N = w.shape[-1]
    out_dtype = out_dtype or x.dtype
    if reduce_axis is not None:
        from ...distributed.tp import (axis_extent, resolve_overlap)
        from ...profiler import stats as _rstats

        mode = resolve_overlap(overlap)
        size = axis_extent(reduce_axis)
        if size == 1:
            # single-shard TP view: the collective would be a no-op —
            # skip it at trace time (the census must stay empty)
            out = stream_linear(x, w, layer=layer, bias=None,
                                scale=scale, activation=None,
                                out_dtype=jnp.float32,
                                act_quant=act_quant)
        elif mode == "ring":
            _rstats.counter("dist.overlap_ring_reduces").inc()
            _rstats.gauge("dist.overlap_ring_phases").set(
                float(size * (size - 1)))
            out = _ring_reduce_pipeline(x, w, layer, scale, act_quant,
                                        reduce_axis, size)
        elif mode == "psum":
            part = stream_linear(x, w, layer=layer, bias=None,
                                 scale=scale, activation=None,
                                 out_dtype=jnp.float32,
                                 act_quant=act_quant)
            out = jax.lax.psum(part, reduce_axis)
        else:
            raise ValueError(
                f"stream_linear: overlap={mode!r} is not 'ring'|'psum'")
        if bias is not None:
            b = bias[0 if layer is None else layer] if stacked else bias
            out = out + b.astype(jnp.float32)
        out = _apply_activation(out, activation)
        return out.astype(out_dtype)
    if act_quant:
        if w.dtype != jnp.int8 or scale is None:
            raise ValueError(
                "stream_linear(act_quant=True) needs int8 weights with "
                "per-output-channel scales (quantize_weight_only_int8)")
        return _stream_linear_act_quant(
            x, w, layer, bias, scale, activation, out_dtype,
            stacked=stacked)
    bn = _pick_bn(K, N, w.dtype.itemsize)
    if bn == 0 or K % 128 != 0 or not _on_tpu():
        # fallback: plain XLA dot (CPU tests, odd shapes)
        wl = w[layer] if stacked else w
        out = jax.lax.dot_general(
            x, wl.astype(x.dtype) if wl.dtype == jnp.int8 else wl,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if scale is not None:
            out = out * (scale[layer] if stacked else scale)
        if bias is not None:
            out = out + (bias[layer] if stacked else bias)
        out = _apply_activation(out, activation)
        return out.astype(out_dtype)

    # odd batches enter the kernel padded to the compute dtype's
    # sublane tile rather than bouncing the whole call back to XLA
    x, M = _sublane_pad(x)
    Mp = x.shape[0]
    nb = N // bn
    has_bias = bias is not None
    has_scale = scale is not None
    # normalize operands to stacked-3D so one kernel serves both forms
    w3 = w if stacked else w[None]
    b3 = None
    s3 = None
    if has_bias:
        b3 = (bias if stacked else bias[None]).reshape(
            w3.shape[0], 1, N)
    if has_scale:
        s3 = (scale if stacked else scale[None]).reshape(
            w3.shape[0], 1, N)
    lidx = jnp.reshape(
        jnp.asarray(0 if layer is None else layer, jnp.int32), (1,))

    def kernel(l_ref, x_ref, *rest):
        del l_ref
        refs = list(rest)
        w_ref = refs.pop(0)
        b_ref = refs.pop(0) if has_bias else None
        s_ref = refs.pop(0) if has_scale else None
        o_ref = refs.pop(0)
        wb = w_ref[0]                                # [K, bn]
        acc = jax.lax.dot_general(
            x_ref[...], wb.astype(x_ref.dtype),
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)      # [Mp, bn]
        if s_ref is not None:
            acc = acc * s_ref[0].astype(jnp.float32)
        if b_ref is not None:
            acc = acc + b_ref[0].astype(jnp.float32)
        acc = _apply_activation(acc, activation)
        o_ref[...] = acc.astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((Mp, K), lambda j, l: (0, 0)),
        pl.BlockSpec((1, K, bn), lambda j, l: (l[0], 0, j)),
    ]
    operands = [x, w3]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, bn), lambda j, l: (l[0], 0, j)))
        operands.append(b3)
    if has_scale:
        in_specs.insert(2 if not has_bias else 3,
                        pl.BlockSpec((1, 1, bn),
                                     lambda j, l: (l[0], 0, j)))
        operands.insert(2 if not has_bias else 3, s3)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((Mp, bn), lambda j, l: (0, j)),
        scratch_shapes=[])
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((Mp, N), out_dtype),
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
        )(lidx, *operands)
    return out[:M] if Mp != M else out


# ---------------------------------------------------------------------
# grouped layer tail: O-proj + LN2 + FFN (+ next layer's LN1 + QKV)
# ---------------------------------------------------------------------


def _mm_like(x, w, scale):
    """The exact matmul math of FusedMultiTransformer._mm (plain dot in
    the compute dtype; int8 weights dequant on the OUTPUT via
    per-output-channel scales) — the grouped XLA fallback mirrors the
    ungrouped decode path bitwise so CPU greedy-parity tests stay
    pinned."""
    if w.dtype == jnp.int8:
        return (x @ w.astype(x.dtype)) * scale.astype(x.dtype)
    return x @ w


def _tail_geometry(Ka, d, dff, nq_n, itemsize):
    """Block widths for the fused tail's weight streams, or None when
    the shapes can't tile (the caller then takes the XLA fallback)."""
    if Ka % 128 or d % 128 or dff % 128:
        return None
    bn_o = _pick_bn(Ka, d, itemsize, _TARGET_GROUPED_BYTES)
    bn_f = _pick_bn(d, dff, itemsize, _TARGET_GROUPED_BYTES)
    if not bn_o or not bn_f:
        return None
    bn_q = 0
    if nq_n:
        if nq_n % 128:
            return None
        bn_q = _pick_bn(d, nq_n, itemsize, _TARGET_GROUPED_BYTES)
        if not bn_q:
            return None
    return bn_o, bn_f, bn_q


def _stream_layer_tail_kernel(att, h, wo3, w13, w23, so3, s13, s23,
                              bo3, b13, b23, ln2s, ln2b, lidx, qg,
                              eps, activation, out_dtype, bns,
                              interpret):
    """The fused tail as ONE Pallas grid over three (four with the
    prefetch phase) weight streams. TPU grids run sequentially, so the
    kernel is phased by ``j = program_id(0)``:

      phase O   (j <  nb_o):          h2[:, blk] = h + att @ Wo_blk
      boundary  (j == nb_o):          hn2 = LN2(h2)   (f32 scratch)
      phase FFN (nb_o <= j < +nb_f):  acc += act(hn2 @ W1_blk) @ W2_blk
      finish    (last FFN block):     h_out = h2 + acc; hn1 = LN1'(h_out)
      phase QKV (j >= nb_o + nb_f):   qkv[:, blk] = hn1 @ Wq_blk

    Every weight stream is auto double-buffered by its BlockSpec, so
    the QKV phase overlaps layer l+1's first weight DMAs with layer
    l's FFN tail still in flight — the cross-layer prefetch. Index
    maps CLAMP each stream to its own phase's range; the off-phase
    block a stream re-fetches is the one already resident, so no extra
    HBM traffic is issued for parked streams."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bn_o, bn_f, bn_q = bns
    Mp, Ka = att.shape
    d = h.shape[1]
    dff = w13.shape[-1]
    nb_o, nb_f = d // bn_o, dff // bn_f
    has_q = qg is not None
    nb_q = (qg["w"].shape[-1] // bn_q) if has_q else 0
    has_s = so3 is not None
    has_sq = has_q and qg.get("s") is not None
    cdtype = att.dtype
    f32 = jnp.float32

    def dot(a, b):
        return jax.lax.dot_general(
            a, b.astype(a.dtype), (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=f32)

    def kernel(l_ref, *rest):
        del l_ref
        refs = list(rest)
        att_r = refs.pop(0)
        h_r = refs.pop(0)
        wo_r = refs.pop(0)
        so_r = refs.pop(0) if has_s else None
        bo_r = refs.pop(0)
        w1_r = refs.pop(0)
        s1_r = refs.pop(0) if has_s else None
        b1_r = refs.pop(0)
        w2_r = refs.pop(0)
        s2_r = refs.pop(0) if has_s else None
        b2_r = refs.pop(0)
        ln2s_r = refs.pop(0)
        ln2b_r = refs.pop(0)
        wq_r = sq_r = bq_r = ln1s_r = ln1b_r = out_q = None
        if has_q:
            wq_r = refs.pop(0)
            sq_r = refs.pop(0) if has_sq else None
            bq_r = refs.pop(0)
            ln1s_r = refs.pop(0)
            ln1b_r = refs.pop(0)
        out_h = refs.pop(0)
        if has_q:
            out_q = refs.pop(0)
        s_h2, s_hn, s_acc = refs
        j = pl.program_id(0)

        @pl.when(j < nb_o)
        def _o_phase():
            blk = dot(att_r[...], wo_r[0])           # [Mp, bn_o] f32
            if so_r is not None:
                blk = blk * so_r[0].astype(f32)
            cols = pl.ds(j * bn_o, bn_o)
            blk = blk + bo_r[0, :, cols].astype(f32)
            s_h2[:, cols] = h_r[:, cols].astype(f32) + blk

        @pl.when(j == nb_o)
        def _ln2_boundary():
            hn = _ln_f32(s_h2[...], ln2s_r[0].astype(f32),
                         ln2b_r[0].astype(f32), eps)
            s_hn[...] = hn.astype(cdtype)
            s_acc[...] = jnp.zeros_like(s_acc)

        @pl.when((j >= nb_o) & (j < nb_o + nb_f))
        def _ffn_phase():
            a = dot(s_hn[...], w1_r[0])              # [Mp, bn_f] f32
            if s1_r is not None:
                a = a * s1_r[0].astype(f32)
            a = _apply_activation(a + b1_r[0].astype(f32), activation)
            s_acc[...] += dot(a.astype(cdtype), w2_r[0])

        @pl.when(j == nb_o + nb_f - 1)
        def _finish():
            acc = s_acc[...]
            if s2_r is not None:
                acc = acc * s2_r[0].astype(f32)
            hout = s_h2[...] + acc + b2_r[0].astype(f32)
            out_h[...] = hout.astype(out_h.dtype)
            if has_q:
                hn1 = _ln_f32(hout, ln1s_r[0].astype(f32),
                              ln1b_r[0].astype(f32), eps)
                s_hn[...] = hn1.astype(cdtype)

        if has_q:
            @pl.when(j >= nb_o + nb_f)
            def _qkv_prefetch_phase():
                qb = dot(s_hn[...], wq_r[0])         # [Mp, bn_q] f32
                if sq_r is not None:
                    qb = qb * sq_r[0].astype(f32)
                out_q[...] = (qb + bq_r[0].astype(f32)) \
                    .astype(out_q.dtype)

    # clamp each stream's block index into its own phase so parked
    # streams keep re-mapping the block already resident in VMEM
    o_idx = lambda j: jnp.minimum(j, nb_o - 1)                # noqa: E731
    f_idx = lambda j: jnp.clip(j - nb_o, 0, nb_f - 1)         # noqa: E731
    q_idx = lambda j: jnp.clip(j - nb_o - nb_f, 0,            # noqa: E731
                               max(nb_q - 1, 0))

    in_specs = [
        pl.BlockSpec((Mp, Ka), lambda j, l: (0, 0)),
        pl.BlockSpec((Mp, d), lambda j, l: (0, 0)),
        pl.BlockSpec((1, Ka, bn_o), lambda j, l: (l[0], 0, o_idx(j))),
    ]
    operands = [att, h, wo3]
    if has_s:
        in_specs.append(pl.BlockSpec((1, 1, bn_o),
                                     lambda j, l: (l[0], 0, o_idx(j))))
        operands.append(so3)
    in_specs.append(pl.BlockSpec((1, 1, d), lambda j, l: (l[0], 0, 0)))
    operands.append(bo3)
    in_specs.append(pl.BlockSpec((1, d, bn_f),
                                 lambda j, l: (l[0], 0, f_idx(j))))
    operands.append(w13)
    if has_s:
        in_specs.append(pl.BlockSpec((1, 1, bn_f),
                                     lambda j, l: (l[0], 0, f_idx(j))))
        operands.append(s13)
    in_specs.append(pl.BlockSpec((1, 1, bn_f),
                                 lambda j, l: (l[0], 0, f_idx(j))))
    operands.append(b13)
    in_specs.append(pl.BlockSpec((1, bn_f, d),
                                 lambda j, l: (l[0], f_idx(j), 0)))
    operands.append(w23)
    if has_s:
        in_specs.append(pl.BlockSpec((1, 1, d),
                                     lambda j, l: (l[0], 0, 0)))
        operands.append(s23)
    in_specs.append(pl.BlockSpec((1, 1, d), lambda j, l: (l[0], 0, 0)))
    operands.append(b23)
    in_specs.append(pl.BlockSpec((1, d), lambda j, l: (l[0], 0)))
    operands.append(ln2s)
    in_specs.append(pl.BlockSpec((1, d), lambda j, l: (l[0], 0)))
    operands.append(ln2b)
    out_shapes = [jax.ShapeDtypeStruct((Mp, d), out_dtype)]
    out_specs = [pl.BlockSpec((Mp, d), lambda j, l: (0, 0))]
    if has_q:
        nq_n = qg["w"].shape[-1]
        in_specs.append(pl.BlockSpec((1, d, bn_q),
                                     lambda j, l: (l[1], 0, q_idx(j))))
        operands.append(qg["w"])
        if has_sq:
            in_specs.append(pl.BlockSpec(
                (1, 1, bn_q), lambda j, l: (l[1], 0, q_idx(j))))
            operands.append(qg["s"])
        in_specs.append(pl.BlockSpec((1, 1, bn_q),
                                     lambda j, l: (l[1], 0, q_idx(j))))
        operands.append(qg["b"])
        in_specs.append(pl.BlockSpec((1, d), lambda j, l: (l[1], 0)))
        operands.append(qg["ln_s"])
        in_specs.append(pl.BlockSpec((1, d), lambda j, l: (l[1], 0)))
        operands.append(qg["ln_b"])
        out_shapes.append(jax.ShapeDtypeStruct((Mp, nq_n), out_dtype))
        out_specs.append(pl.BlockSpec((Mp, bn_q),
                                      lambda j, l: (0, q_idx(j))))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb_o + nb_f + nb_q,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((Mp, d), f32),      # s_h2: post-attention hidden
            pltpu.VMEM((Mp, d), cdtype),   # s_hn: LN'd matmul input
            pltpu.VMEM((Mp, d), f32),      # s_acc: FFN2 accumulator
        ])
    with _enable_x64(False):
        outs = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shapes,
            compiler_params=_pltpu_compiler_params(pltpu)(
                vmem_limit_bytes=KERNEL_VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(lidx, *operands)
    return outs


def _tail_fallback(att, h, wo, w1, w2, layer, so, s1, s2, bo, b1, b2,
                   ln2_scale, ln2_bias, eps, activation, qg, out_dtype,
                   stacked):
    """XLA composition of the identical math (CPU CI, ragged shapes):
    op-for-op the ungrouped decode path (_layer_body + _mm), so the
    grouped CPU engine reproduces the ungrouped greedy tokens."""
    def at(a):
        return a[layer] if (stacked and a is not None) else a

    h2 = (h + _mm_like(att, at(wo), at(so)) + at(bo)).astype(h.dtype)
    hn = _ln_f32(h2, at(ln2_scale), at(ln2_bias), eps).astype(h.dtype)
    ff = _apply_activation(
        (_mm_like(hn, at(w1), at(s1)) + at(b1)).astype(h.dtype),
        activation)
    h_out = (h2 + _mm_like(ff, at(w2), at(s2)) + at(b2)).astype(h.dtype)
    if qg is None:
        return h_out.astype(out_dtype)
    lq = qg.get("layer")

    def atq(a):
        return a[lq] if (stacked and a is not None and lq is not None) \
            else a

    hn1 = _ln_f32(h_out, atq(qg["ln_s"]), atq(qg["ln_b"]), eps) \
        .astype(h.dtype)
    qkv = _mm_like(hn1, atq(qg["w"]), atq(qg.get("s"))) + atq(qg["b"])
    return h_out.astype(out_dtype), qkv.astype(out_dtype)


def _tail_tp_split(att, h, wo, w1, w2, layer, so, s1, s2, bo, b1, b2,
                   ln2_scale, ln2_bias, eps, activation, next_qkv,
                   out_dtype, stacked, reduce_axis, overlap):
    """Tensor-parallel grouped tail (ISSUE 19): the fused Pallas grid
    cannot span a collective, so under a ``reduce_axis`` the tail
    SPLITS at the two reduction points into streamed calls — O-proj
    partial reduced (ring phases riding under the FFN1 weight stream
    that follows), FFN1, FFN2 partial reduced, and the cross-layer
    QKV prefetch emitted AFTER the FFN2 reduction so its weight DMA
    overlaps the trailing ring phases. Op-for-op the ungrouped TP
    decode math (stream_linear reduce_axis= calls), so grouped-TP
    greedy tokens reproduce the four-call form's exactly."""
    l = (0 if layer is None else layer) if stacked else None

    def at(a):
        return a[l] if (stacked and a is not None) else a

    h2 = (h + stream_linear(
        att, wo, layer=layer, bias=bo, scale=so, out_dtype=h.dtype,
        reduce_axis=reduce_axis, overlap=overlap)).astype(h.dtype)
    hn = _ln_f32(h2, at(ln2_scale), at(ln2_bias), eps).astype(h.dtype)
    ff = stream_linear(hn, w1, layer=layer, bias=b1, scale=s1,
                       activation=activation, out_dtype=h.dtype)
    h_out = (h2 + stream_linear(
        ff, w2, layer=layer, bias=b2, scale=s2, out_dtype=h.dtype,
        reduce_axis=reduce_axis, overlap=overlap)).astype(h.dtype)
    if next_qkv is None:
        return h_out.astype(out_dtype)
    lq = next_qkv.get("layer")
    lq = (0 if lq is None else lq) if stacked else None

    def atq(a):
        return a[lq] if (stacked and a is not None) else a

    hn1 = _ln_f32(h_out, atq(next_qkv["ln_s"]), atq(next_qkv["ln_b"]),
                  eps).astype(h.dtype)
    qkv = stream_linear(hn1, next_qkv["w"], layer=next_qkv.get("layer"),
                        bias=next_qkv["b"], scale=next_qkv.get("s"),
                        out_dtype=h.dtype)
    return h_out.astype(out_dtype), qkv.astype(out_dtype)


def stream_layer_tail(att, h, wo, w1, w2, layer=None, *, bo, b1, b2,
                      ln2_scale, ln2_bias, epsilon, activation=None,
                      so=None, s1=None, s2=None, next_qkv=None,
                      out_dtype=None, interpret=None,
                      reduce_axis=None, overlap=None):
    """GROUPED streamed layer tail: everything after attention in one
    call — ``h2 = h + att @ Wo + bo; h_out = h2 + FFN(LN2(h2))`` — and,
    when ``next_qkv`` is given, the CROSS-LAYER PREFETCH phase
    ``qkv' = LN1'(h_out) @ Wq' + bq'`` for the next layer, so the
    decode fori_loop issues ONE streamed call per layer.

    att [M, Ka], h [M, d]. Weights stacked [L, K, N] with a traced
    ``layer`` index, or unstacked 2-D. ``so/s1/s2``: int8
    per-output-channel dequant scales [(L,) N] — the grouped kernel
    streams int8 and dequants in-kernel (weight-only math; full A8W8
    act-quant stays on the ungrouped kernel). ``next_qkv``: dict with
    ``w``, ``b``, ``ln_s``, ``ln_b`` (+ optional ``s`` scale and
    ``layer`` index for the stacked form — pass ``min(l+1, L-1)``).

    Returns ``h_out`` (and ``qkv_next`` when ``next_qkv``), in
    ``out_dtype`` (default: h.dtype). Off-TPU / ragged shapes take an
    XLA fallback with op-for-op ungrouped math; ``interpret=True``
    forces the Pallas kernel in interpret mode (the parity tests).

    ``reduce_axis``/``overlap``: the tensor-parallel grouped tail —
    ``wo``/``w2`` are row-parallel [K/mp, N] shards whose f32 partials
    reduce over the named axis (``overlap="ring"`` pipelines the
    reduction as ppermute chunks under the following weight stream,
    ``"psum"`` is the blocking reference, None reads
    ``FLAGS_tp_overlap``); a collective cannot live inside the fused
    Pallas grid, so this form splits into streamed calls at the two
    reduction points (``_tail_tp_split``).
    """
    out_dtype = out_dtype or h.dtype
    stacked = wo.ndim == 3
    if (w1.ndim != wo.ndim or w2.ndim != wo.ndim
            or (next_qkv is not None
                and next_qkv["w"].ndim != wo.ndim)):
        raise ValueError("stream_layer_tail: wo/w1/w2 (and next_qkv.w) "
                         "must all be stacked [L, K, N] or all 2-D")
    scales = (so, s1, s2)
    if any(s is not None for s in scales) and \
            not all(s is not None for s in scales):
        raise ValueError("stream_layer_tail: pass all of so/s1/s2 or "
                         "none (the engine quantizes all four stacks)")
    if reduce_axis is not None:
        return _tail_tp_split(
            att, h, wo, w1, w2, layer, so, s1, s2, bo, b1, b2,
            ln2_scale, ln2_bias, epsilon, activation, next_qkv,
            out_dtype, stacked, reduce_axis, overlap)
    Ka = att.shape[1]
    d = h.shape[1]
    dff = w1.shape[-1]
    nq_n = next_qkv["w"].shape[-1] if next_qkv is not None else 0
    bns = _tail_geometry(Ka, d, dff, nq_n, wo.dtype.itemsize)
    use_kernel = bns is not None and (interpret is True or _on_tpu())
    if not use_kernel:
        return _tail_fallback(
            att, h, wo, w1, w2,
            (0 if layer is None else layer) if stacked else None,
            so, s1, s2, bo, b1, b2, ln2_scale, ln2_bias, epsilon,
            activation, next_qkv, out_dtype, stacked)

    interpret = bool(interpret) if interpret is not None \
        else not _on_tpu()
    L = wo.shape[0] if stacked else 1

    def norm_w(a):
        return a if stacked else a[None]

    def norm_v(a, n):
        return (a if stacked else a[None]).reshape(L, 1, n)

    def norm_ln(a):
        return (a if stacked else a[None]).reshape(L, d)

    qg = None
    lq = 0
    if next_qkv is not None:
        Lq = next_qkv["w"].shape[0] if stacked else 1
        lq = next_qkv.get("layer")
        lq = 0 if lq is None else lq
        qg = {
            "w": norm_w(next_qkv["w"]),
            "b": (next_qkv["b"] if stacked else next_qkv["b"][None])
            .reshape(Lq, 1, nq_n),
            "ln_s": norm_ln(next_qkv["ln_s"]),
            "ln_b": norm_ln(next_qkv["ln_b"]),
        }
        if next_qkv.get("s") is not None:
            qg["s"] = (next_qkv["s"] if stacked
                       else next_qkv["s"][None]).reshape(Lq, 1, nq_n)
    lidx = jnp.stack([
        jnp.asarray(0 if layer is None else layer, jnp.int32),
        jnp.asarray(lq, jnp.int32)])

    attp, M = _sublane_pad(att)
    hp, _ = _sublane_pad(h)
    outs = _stream_layer_tail_kernel(
        attp, hp, norm_w(wo), norm_w(w1), norm_w(w2),
        norm_v(so, d) if so is not None else None,
        norm_v(s1, dff) if s1 is not None else None,
        norm_v(s2, d) if s2 is not None else None,
        norm_v(bo, d), norm_v(b1, dff), norm_v(b2, d),
        norm_ln(ln2_scale), norm_ln(ln2_bias), lidx, qg,
        epsilon, activation, out_dtype, bns, interpret)
    h_out, qkv = (outs[0], outs[1]) if next_qkv is not None \
        else (outs[0], None)
    if h_out.shape[0] != M:
        h_out = h_out[:M]
        qkv = qkv[:M] if qkv is not None else None
    return h_out if qkv is None else (h_out, qkv)
