"""Parameter initializers.

TPU-native equivalent of the reference's initializer suite
(reference: python/paddle/nn/initializer/*.py — Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign, Orthogonal, Dirac). Initializers are callables mapping
(shape, dtype) -> jax array; Layer.create_parameter invokes them with the
framework's stateful Generator so results are reproducible under
``paddle.seed``.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.generator import default_generator

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    """Recommended gain per nonlinearity (parity with the reference's
    paddle.nn.initializer.calculate_gain)."""
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return gains[nonlinearity]


def _fan_in_out(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # Linear weight is stored [in, out] (paddle convention)
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError

    def _key(self):
        return default_generator().next_key()


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(shape, self.value, convert_dtype(dtype).np_dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        return (jax.random.normal(self._key(), shape, jnp.float32) * self.std
                + self.mean).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        x = jax.random.truncated_normal(self._key(), self.a, self.b, shape,
                                        jnp.float32)
        return (x * self.std + self.mean).astype(dt)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        return jax.random.uniform(
            self._key(), shape, jnp.float32, self.low, self.high).astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(self._key(), shape, jnp.float32) * std).astype(dt)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            self._key(), shape, jnp.float32, -limit, limit).astype(dt)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(self._key(), shape, jnp.float32) * std).astype(dt)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(
            self._key(), shape, jnp.float32, -limit, limit).astype(dt)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        arr = jnp.asarray(np.asarray(self.value), dt)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        if len(shape) < 2:
            raise ValueError("Orthogonal initializer needs >=2 dims")
        rows = int(shape[0])
        cols = int(np.prod(shape[1:]))
        n = max(rows, cols)
        a = jax.random.normal(self._key(), (n, n), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diag(r))
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dt)


class Dirac(Initializer):
    """Identity-preserving conv kernel init (reference: nn/initializer/dirac.py)."""

    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        dt = convert_dtype(dtype).np_dtype
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        min_c = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for c in range(min_c):
                idx = (g * (oc // self.groups) + c, c, *centers)
                out[idx] = 1.0
        return jnp.asarray(out, dt)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Mirror paddle.nn.initializer.set_global_initializer."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _default_weight_init():
    return _global_weight_init if _global_weight_init is not None else XavierNormal()


def _default_bias_init():
    return _global_bias_init if _global_bias_init is not None else Constant(0.0)
