"""Layer: the module base class.

TPU-native equivalent of the reference's ``paddle.nn.Layer``
(reference: python/paddle/nn/layer/layers.py — parameter/sublayer/buffer
registries, hooks, train/eval, state_dict). Parameters are eager
``Parameter`` tensors over PJRT buffers; a Layer is a pytree-of-parameters
owner whose ``forward`` composes eager ops, so the same code path traces
under ``paddle_tpu.jit.to_static`` into one XLA program.
"""
from __future__ import annotations

import itertools
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Parameter, Tensor
from . import initializer as I

__all__ = ["Layer", "ParamAttr", "Sequential", "LayerList", "ParameterList",
           "LayerDict"]

_hook_id = itertools.count()


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"cannot convert {type(attr)} to ParamAttr")


class HookRemoveHelper:
    def __init__(self, hooks: dict, hid: int):
        self._hooks, self._hid = hooks, hid

    def remove(self):
        self._hooks.pop(self._hid, None)


_layer_instance_counters: Dict[str, int] = {}


class Layer:
    def __init__(self, name_scope: str = None, dtype: str = "float32"):
        self.training = True
        self._dtype = dtype
        cls_tag = (name_scope or self.__class__.__name__).lower()
        idx = _layer_instance_counters.get(cls_tag, 0)
        _layer_instance_counters[cls_tag] = idx + 1
        # stable structured name, reference-style ("linear_0"): derived
        # from per-class construction order, reproducible across processes
        # (reference: base/unique_name.py + Layer.full_name)
        self._full_name = f"{cls_tag}_{idx}"
        self._parameters: Dict[str, Optional[Parameter]] = OrderedDict()
        self._sub_layers: Dict[str, Optional["Layer"]] = OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = OrderedDict()
        self._casted_by_pure_fp16 = False

    # ------------- parameter creation -------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype or get_default_dtype().name
        init = attr.initializer or default_initializer or (
            I._default_bias_init() if is_bias else I._default_weight_init())
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, dtype=None):
        dtype = dtype or self._dtype
        return Tensor(jnp.zeros((), convert_dtype(dtype).np_dtype), name=name)

    # ------------- registry magic -------------
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self._assign_structured_name(name, value)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
                raise TypeError(f"cannot assign {type(value)} to parameter {name!r}")
            if layers is not None and name in layers and value is None:
                layers[name] = None
                return
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")

    def __delattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    def _assign_structured_name(self, attr_name: str, p: Parameter):
        """Replace an auto-generated tensor name with a stable structured
        one ("linear_0.weight") so optimizer/checkpoint state keyed by
        p.name survives process restarts (reference: stable param names
        like linear_0.w_0 from unique_name generators)."""
        if p is not None and p.name.startswith("generated_tensor_"):
            p.name = f"{getattr(self, '_full_name', 'layer')}.{attr_name}"

    # ------------- explicit registration -------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._assign_structured_name(name, parameter)
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: Optional["Layer"]):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError("add_sublayer expects a Layer")
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------- traversal -------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + "." + pname if name else pname), p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + "." + bname if name else bname), b

    def _traverse(self, prefix: str, include_sublayers: bool):
        yield prefix, self
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = prefix + "." + lname if prefix else lname
                yield from sub._traverse(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = prefix + "." + name if prefix else name
            yield from sub.named_sublayers(prefix=p, include_self=True)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ------------- mode -------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # ------------- hooks -------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        hid = next(_hook_id)
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        hid = next(_hook_id)
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # ------------- call -------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    # ------------- state dict -------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        seen = set()
        for prefix, layer in self._traverse("", include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                if bname in layer._non_persistable_buffer_names:
                    continue
                full = prefix + "." + bname if prefix else bname
                dest[structured_name_prefix + full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            t = own[k]
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(arr.shape) != tuple(t._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {arr.shape} vs {t._data.shape}")
            t._rebind(arr.astype(t._data.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    # paddle aliases
    load_dict = set_state_dict
    set_dict = set_state_dict

    # ------------- dtype / device movement -------------
    def _transform(self, fn):
        for _, p in self.named_parameters():
            p._rebind(fn(p._data))
        for _, b in self.named_buffers():
            b._rebind(fn(b._data))
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            np_dt = convert_dtype(dtype).np_dtype
            self._transform(
                lambda a: a.astype(np_dt)
                if jnp.issubdtype(a.dtype, jnp.floating) else a)
            self._dtype = convert_dtype(dtype).name
        if device is not None:
            from ..core.place import Place
            if isinstance(device, Place):
                dev = device.jax_device()
                import jax as _jax
                self._transform(lambda a: _jax.device_put(a, dev))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def full_name(self):
        return self._full_name

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + ("\n  ".join(sub_repr)))
        main = self.__class__.__name__ + "(" + extra
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class Sequential(Layer):
    """reference: python/paddle/nn/layer/container.py Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                len(layers[0]) and isinstance(layers[0][0], tuple):
            for name, l in layers[0]:
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        n = len(self)
        if idx < 0:
            idx += n
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __setitem__(self, idx, p):
        self._parameters[str(idx)] = p

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, p):
        self.add_parameter(str(len(self)), p)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        l = self._sub_layers[key]
        del self._sub_layers[key]
        return l

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, OrderedDict, LayerDict)) else sublayers
        for k, v in items:
            self[k] = v
        return self
