from . import (  # noqa: F401
    activation, common, conv, loss, norm, pooling, rnn, transformer,
)
