"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = [
    "ReLU", "ReLU6", "GELU", "Sigmoid", "LogSigmoid", "Silu", "Swish",
    "Mish", "Softmax", "LogSoftmax", "Softplus", "Softshrink", "Hardshrink",
    "Tanhshrink", "Hardsigmoid", "Hardswish", "Hardtanh", "LeakyReLU", "ELU",
    "CELU", "SELU", "PReLU", "RReLU", "GLU", "Tanh", "Maxout", "Softsign",
    "ThresholdedReLU",
]


def _simple(name, fn_name, **defaults):
    def __init__(self, name=None, **kwargs):
        Layer.__init__(self)
        self._kwargs = {**defaults, **kwargs}

    def forward(self, x):
        return getattr(F, fn_name)(x, **self._kwargs)

    cls = type(name, (Layer,), {"__init__": __init__, "forward": forward})
    return cls


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
Sigmoid = _simple("Sigmoid", "sigmoid")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Softsign = _simple("Softsign", "softsign")
Hardswish = _simple("Hardswish", "hardswish")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self._approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self._axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, beta=self._beta, threshold=self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, threshold=self._threshold)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, threshold=self._threshold)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, min=self._min, max=self._max)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, negative_slope=self._slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, alpha=self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, alpha=self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, scale=self._scale, alpha=self._alpha)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, lower=self._lower, upper=self._upper,
                       training=self.training)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, axis=self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, groups=self._groups, axis=self._axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self._threshold, self._value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, threshold=self._threshold,
                                  value=self._value)
