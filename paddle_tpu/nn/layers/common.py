"""Common layers: Linear, Embedding, Dropout, padding, upsampling.

TPU-native equivalent of the reference's common layers
(reference: python/paddle/nn/layer/common.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = [
    "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D",
    "AlphaDropout", "Flatten", "Identity", "Pad1D", "Pad2D", "Pad3D",
    "ZeroPad2D", "Upsample", "UpsamplingNearest2D", "UpsamplingBilinear2D",
    "PixelShuffle", "PixelUnshuffle", "ChannelShuffle", "CosineSimilarity",
    "Bilinear", "Unfold", "Fold", "Linear_",
]


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


Linear_ = Linear


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            with_pad = self.weight._data.at[padding_idx].set(0.0)
            self.weight._rebind(with_pad)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class _PadNd(Layer):
    def __init__(self, padding, mode, value, data_format):
        super().__init__()
        self._padding = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._padding, mode=self._mode, value=self._value,
                     data_format=self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, upscale_factor=self.factor,
                               data_format=self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, downscale_factor=self.factor,
                                 data_format=self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, groups=self.groups,
                                 data_format=self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=I.XavierNormal(
                fan_in=in1_features + in2_features, fan_out=out_features))
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes, self.kernel_sizes = output_sizes, kernel_sizes
        self.strides, self.paddings = strides, paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)
