"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
    "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
    "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "RMSNorm",
    "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. In compiled data-parallel steps the batch
    axis spans the mesh and XLA's batch-norm-expander + sharding already
    reduce over all replicas; eager single-process falls back to local stats
    (reference: python/paddle/nn/layer/norm.py SyncBatchNorm over NCCL).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new.register_buffer("_mean", layer._mean)
            new.register_buffer("_variance", layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """LLM-standard RMSNorm (the fork's fused rmsnorm path)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self._data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self._data_format)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (reference:
    python/paddle/nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        import numpy as np
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            shape=[h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            shape=[w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...ops.dispatch import eager_apply, as_tensor_args

        dim, iters, eps = self._dim, self._power_iters, self._eps
        u0, v0 = self.weight_u._data, self.weight_v._data

        def raw(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma

        out = eager_apply("spectral_norm", raw, as_tensor_args(weight))
        return out
