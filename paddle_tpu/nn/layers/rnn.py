"""Recurrent layers.

TPU-native equivalent of the reference's RNN stack (reference:
python/paddle/nn/layer/rnn.py — RNNCellBase, SimpleRNNCell/LSTMCell/GRUCell,
RNN/BiRNN wrappers, multi-layer LSTM/GRU/SimpleRNN backed by cudnn kernels).
Here the recurrence is a ``lax.scan`` — the XLA-native loop construct — so
the whole unrolled sequence compiles to one fused while-loop on TPU instead
of per-step kernel launches.

Weight convention matches the reference: weight_ih [gates*h, in],
weight_hh [gates*h, h], gate order LSTM=(i,f,c,o) (phi lstm kernel order),
GRU=(r,z,c).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import eager_apply, as_tensor_args
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU", "RNNCellBase"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        import paddle_tpu as paddle

        batch = batch_ref.shape[batch_dim_idx]
        if isinstance(self.state_shape[0], (list, tuple)):
            return tuple(
                paddle.full([batch] + list(s), init_value, dtype or "float32")
                for s in self.state_shape)
        return paddle.full([batch] + list(self.state_shape), init_value,
                           dtype or "float32")


def _cell_params(layer, input_size, hidden_size, gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
    std = 1.0 / math.sqrt(hidden_size)
    u = I.Uniform(-std, std)
    layer.weight_ih = layer.create_parameter(
        [gates * hidden_size, input_size], weight_ih_attr,
        default_initializer=u)
    layer.weight_hh = layer.create_parameter(
        [gates * hidden_size, hidden_size], weight_hh_attr,
        default_initializer=u)
    layer.bias_ih = layer.create_parameter(
        [gates * hidden_size], bias_ih_attr, is_bias=True,
        default_initializer=u)
    layer.bias_hh = layer.create_parameter(
        [gates * hidden_size], bias_hh_attr, is_bias=True,
        default_initializer=u)


def _lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    z = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xz = x @ w_ih.T + b_ih
    hz = h @ w_hh.T + b_hh
    xr, xu, xc = jnp.split(xz, 3, axis=-1)
    hr, hu, hc = jnp.split(hz, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    u = jax.nn.sigmoid(xu + hu)
    c = jnp.tanh(xc + r * hc)
    return (1 - u) * c + u * h


def _rnn_step(x, h, w_ih, w_hh, b_ih, b_hh, activation):
    z = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    return jnp.tanh(z) if activation == "tanh" else jax.nn.relu(z)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = self.activation

        def raw(x, h, w_ih, w_hh, b_ih, b_hh):
            return _rnn_step(x, h, w_ih, w_hh, b_ih, b_hh, act)

        out = eager_apply("simple_rnn_cell", raw, as_tensor_args(
            inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh))
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 4, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def raw(x, hh, cc, w_ih, w_hh, b_ih, b_hh):
            return _lstm_step(x, hh, cc, w_ih, w_hh, b_ih, b_hh)

        h_new, c_new = eager_apply("lstm_cell", raw, as_tensor_args(
            inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh), n_outputs=2)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 3, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def raw(x, h, w_ih, w_hh, b_ih, b_hh):
            return _gru_step(x, h, w_ih, w_hh, b_ih, b_hh)

        out = eager_apply("gru_cell", raw, as_tensor_args(
            inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh))
        return out, out


class RNN(Layer):
    """Run a cell over a sequence (reference rnn.py RNN); python loop over
    steps in eager, trace-friendly for to_static."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle

        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = []
        for t in order:
            x_t = inputs[:, t] if time_axis == 1 else inputs[t]
            out, states = self.cell(x_t, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = paddle.stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle

        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, fin_fw = self.rnn_fw(inputs, st_fw)
        out_bw, fin_bw = self.rnn_bw(inputs, st_bw)
        out = paddle.concat([out_fw, out_bw], axis=-1)
        return out, (fin_fw, fin_bw)


class _MultiLayerRNN(Layer):
    """Stacked (optionally bidirectional) recurrence as a single fused
    ``lax.scan`` per layer-direction."""

    MODE_GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        if direction in ("forward",):
            self.num_directions = 1
        elif direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        else:
            raise ValueError(f"unknown direction {direction!r}")
        self.direction = direction

        gates = self.MODE_GATES[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._param_names = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 \
                    else hidden_size * self.num_directions
                suffix = f"_reverse" if d == 1 else ""
                names = [f"weight_ih_l{layer}{suffix}",
                         f"weight_hh_l{layer}{suffix}",
                         f"bias_ih_l{layer}{suffix}",
                         f"bias_hh_l{layer}{suffix}"]
                shapes = [[gates * hidden_size, in_sz],
                          [gates * hidden_size, hidden_size],
                          [gates * hidden_size], [gates * hidden_size]]
                attrs = [weight_ih_attr, weight_hh_attr, bias_ih_attr,
                         bias_hh_attr]
                for n, s, a in zip(names, shapes, attrs):
                    p = self.create_parameter(s, a, is_bias=(len(s) == 1),
                                              default_initializer=u)
                    self.add_parameter(n, p)
                self._param_names.append(names)

    @property
    def state_components(self):
        return 2 if self.mode == "LSTM" else 1

    def _scan_one(self, x, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse):
        """x: [T, B, in]; returns (outputs [T, B, H], h_T, c_T)."""
        mode, act = self.mode, self.activation

        def step(carry, x_t):
            if mode == "LSTM":
                h, c = carry
                h_new, c_new = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh)
                return (h_new, c_new), h_new
            h = carry[0]
            if mode == "GRU":
                h_new = _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh)
            else:
                h_new = _rnn_step(x_t, h, w_ih, w_hh, b_ih, b_hh,
                                  "tanh" if mode == "RNN_TANH" else "relu")
            return (h_new,), h_new

        init = (h0, c0) if mode == "LSTM" else (h0,)
        carry, ys = lax.scan(step, init, x, reverse=bool(reverse))
        if reverse:
            pass  # lax.scan(reverse=True) already emits outputs in orig order
        if mode == "LSTM":
            return ys, carry[0], carry[1]
        return ys, carry[0], carry[0]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        mode = self.mode

        params = []
        flat_names = []
        for names in self._param_names:
            for n in names:
                params.append(self._parameters[n])
                flat_names.append(n)

        has_init = initial_states is not None
        init_tensors = []
        if has_init:
            if mode == "LSTM":
                init_tensors = [initial_states[0], initial_states[1]]
            else:
                init_tensors = [initial_states]

        dropout = self.dropout if self.training else 0.0
        dkeys = None
        if dropout > 0.0 and nl > 1:
            from ...core.generator import next_rng_key
            dkeys = [next_rng_key() for _ in range(nl - 1)]

        def raw(x, *rest):
            n_par = len(params)
            ws = rest[:n_par]
            inits = rest[n_par:]
            xt = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, in]
            b = xt.shape[1]
            if inits:
                if mode == "LSTM":
                    h0_all = inits[0]  # [nl*nd, B, H]
                    c0_all = inits[1]
                else:
                    h0_all = inits[0]
                    c0_all = h0_all
            else:
                h0_all = jnp.zeros((nl * nd, b, hs), xt.dtype)
                c0_all = h0_all
            layer_in = xt
            h_finals, c_finals = [], []
            for layer in range(nl):
                outs_dirs = []
                for d in range(nd):
                    idx = layer * nd + d
                    w_ih, w_hh, b_ih, b_hh = ws[4 * idx: 4 * idx + 4]
                    ys, h_f, c_f = self._scan_one(
                        layer_in, h0_all[idx], c0_all[idx], w_ih, w_hh, b_ih,
                        b_hh, reverse=(d == 1))
                    outs_dirs.append(ys)
                    h_finals.append(h_f)
                    c_finals.append(c_f)
                layer_in = outs_dirs[0] if nd == 1 else \
                    jnp.concatenate(outs_dirs, axis=-1)
                if dkeys is not None and layer < nl - 1:
                    keep = jax.random.bernoulli(dkeys[layer], 1.0 - dropout,
                                                layer_in.shape)
                    layer_in = layer_in * keep.astype(layer_in.dtype) / (1.0 - dropout)
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(h_finals, 0)
            c_stack = jnp.stack(c_finals, 0)
            if mode == "LSTM":
                return out, h_stack, c_stack
            return out, h_stack

    # three tensor outputs for LSTM, two otherwise
        n_out = 3 if mode == "LSTM" else 2
        tensors = as_tensor_args(inputs, *params, *init_tensors)
        res = eager_apply(f"rnn_{mode.lower()}", raw, tensors, n_outputs=n_out)
        if mode == "LSTM":
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kw)


class LSTM(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)
