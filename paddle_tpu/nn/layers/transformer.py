"""Transformer layers.

TPU-native equivalent of the reference's transformer stack (reference:
python/paddle/nn/layer/transformer.py — MultiHeadAttention with
Cache/StaticCache incremental decoding, encoder/decoder layers).
Attention runs through nn.functional.scaled_dot_product_attention, which
picks the Pallas flash kernel on TPU.
"""
from __future__ import annotations

import collections
import copy

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..layer_base import Layer, LayerList
from .common import Dropout, Linear
from .norm import LayerNorm

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


def _convert_attn_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype.name == "bool":
        from ...ops.dispatch import eager_apply, as_tensor_args

        return eager_apply(
            "attn_mask_cast",
            lambda m: jnp.where(m, 0.0, jnp.finfo(jnp.float32).min),
            as_tensor_args(attn_mask))
    return attn_mask


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim

        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _reshape_heads(self, x):
        # [B, S, E] -> [B, S, H, D]
        b, s = x.shape[0], x.shape[1]
        return x.reshape([b, s, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._reshape_heads(self.k_proj(key))
            v = self._reshape_heads(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        # incremental decode cache seeded empty or from key
        if value is None:
            b = key.shape[0]
            import paddle_tpu as paddle
            k = paddle.zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
            v = paddle.zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
            return self.Cache(k, v)
        return self.Cache(key, value)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        # NOTE (r4): a fused-QKV fast path (runtime concat of the three
        # projection weights into one [d, 3d] matmul) was tried here and
        # REMOVED: measured 59.8k vs 61.9k tok/s on the bert-base rung —
        # under whole-step jit the per-step weight concat (fwd + its
        # transpose in bwd) costs more than the wide dot saves, and XLA
        # already schedules the three separate projections well.
        key = query if key is None else key
        value = key if value is None else value

        q = self._reshape_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._reshape_heads(self.k_proj(key))
            v = self._reshape_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                import paddle_tpu as paddle
                k = paddle.concat([cache.k, k], axis=1)
                v = paddle.concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)

        attn_mask = _convert_attn_mask(attn_mask, None)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            is_causal=False, training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = out.reshape([b, s, self.embed_dim])
        out = self.out_proj(out)

        outs = [out]
        if self.need_weights:
            outs.append(None)
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask,
                                                    cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)

        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src, type=MultiHeadAttention.Cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [encoder_layer if i == 0 else copy.deepcopy(encoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, new_cache = layer(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory,
                                               type=MultiHeadAttention.Cache)
        static = self.cross_attn.gen_cache(memory, memory,
                                           type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer if i == 0 else copy.deepcopy(decoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = layer(output, memory, tgt_mask,
                                          memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import paddle_tpu as paddle
        import numpy as np

        m = np.triu(np.full((length, length), float(np.finfo(np.float32).min),
                            np.float32), k=1)
        return paddle.to_tensor(m)
