"""nn.utils (reference: python/paddle/nn/utils — clip_grad helpers,
parameters_to_vector / vector_to_parameters, weight_norm)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .clip import clip_grad_norm_, clip_grad_value_  # noqa: F401

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm"]


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate([p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    arr = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = p.size
        p._rebind(arr[offset:offset + n].reshape(p._data.shape).astype(
            p._data.dtype))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `weight` as g * v/|v| (reference:
    python/paddle/nn/utils/weight_norm_hook.py)."""
    import numpy as np
    from .layer_base import Layer

    weight = getattr(layer, name)
    w = weight._data
    if dim is None:
        norm = jnp.sqrt(jnp.sum(jnp.square(w)))
        g0 = norm.reshape(1)
    else:
        axes = tuple(i for i in range(w.ndim) if i != dim)
        g0 = jnp.sqrt(jnp.sum(jnp.square(w), axis=axes))
    from ..core.tensor import Parameter

    g = Parameter(g0)
    v = Parameter(w)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def _compute(layer_, _inputs):
        vv, gg = layer_._parameters[name + "_v"], layer_._parameters[name + "_g"]
        from ..ops.dispatch import eager_apply

        def raw(varr, garr):
            if dim is None:
                nrm = jnp.sqrt(jnp.sum(jnp.square(varr)))
                return varr * (garr.reshape(()) / nrm)
            axes_ = tuple(i for i in range(varr.ndim) if i != dim)
            nrm = jnp.sqrt(jnp.sum(jnp.square(varr), axis=axes_, keepdims=True))
            shape = [1] * varr.ndim
            shape[dim] = -1
            return varr * (garr.reshape(shape) / nrm)

        w_t = eager_apply("weight_norm", raw, [vv, gg])
        object.__setattr__(layer_, "_wn_" + name, w_t)
        layer_._parameters.pop(name, None)
        layer_.__dict__[name] = w_t

    hook = layer.register_forward_pre_hook(_compute)
    layer.__dict__["_weight_norm_hook_" + name] = hook
    _compute(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    hook = layer.__dict__.pop("_weight_norm_hook_" + name, None)
    if hook is not None:
        hook.remove()
    v = layer._parameters.pop(name + "_v", None)
    g = layer._parameters.pop(name + "_g", None)
    if v is not None and g is not None:
        w = layer.__dict__.pop(name, None)
        from ..core.tensor import Parameter

        layer.add_parameter(name, Parameter(w._data if w is not None else v._data))
    return layer
