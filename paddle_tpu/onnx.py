"""paddle.onnx — export surface (reference: python/paddle/onnx/export.py
delegates to the external paddle2onnx package)."""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """The reference shells out to paddle2onnx (not available here, and
    ONNX is a GPU/CPU-deployment interchange). The TPU deployment
    artifact is portable StableHLO — use ``paddle.jit.save`` and load
    with ``paddle.inference.Config``/``create_predictor``."""
    raise NotImplementedError(
        "ONNX export is not part of the TPU build; use paddle.jit.save "
        "(StableHLO artifact) + paddle.inference.create_predictor for "
        "deployment")
