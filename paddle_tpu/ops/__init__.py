"""Op layer: functional API over jnp with eager autograd dispatch.

The import order matters: each module registers ops + Tensor methods.
"""
from . import dispatch, registry  # noqa: F401
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from . import indexing  # noqa: F401

from . import creation, extras, linalg, logic, manipulation, math, random  # noqa: F401

__all__ = (
    list(creation.__all__) + list(math.__all__) + list(manipulation.__all__)
    + list(logic.__all__) + list(linalg.__all__) + list(random.__all__)
    + list(extras.__all__)
)
