"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype, get_default_dtype, to_jax_dtype
from ..core.tensor import Tensor, to_tensor
from .registry import register_op

__all__ = [
    "zeros", "ones", "full", "empty", "zeros_like", "ones_like", "full_like",
    "empty_like", "arange", "linspace", "logspace", "eye", "tril", "triu",
    "diag", "diagflat", "meshgrid", "assign", "clone", "numel",
    "to_tensor", "tril_indices", "triu_indices", "one_hot",
]


def _dt(dtype):
    if dtype is None:
        return get_default_dtype().np_dtype
    return to_jax_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = get_default_dtype() if isinstance(fill_value, float) else None
    d = _dt(dtype) if dtype is not None else None
    return Tensor(jnp.full(_shape(shape), fill_value, d))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jnp.zeros_like(x._data, dtype=_dt(dtype) if dtype else None))


def ones_like(x, dtype=None, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jnp.ones_like(x._data, dtype=_dt(dtype) if dtype else None))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jnp.full_like(x._data, fill_value,
                                dtype=_dt(dtype) if dtype else None))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if all(isinstance(v, (int, np.integer))
                                for v in (start, end, step))
                 else get_default_dtype())
    return Tensor(jnp.arange(start, end, step, to_jax_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.linspace(float(start), float(stop), int(num),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.logspace(float(start), float(stop), int(num),
                               base=float(base), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype)))


def tril(x, diagonal=0, name=None) -> Tensor:
    from .dispatch import eager_apply

    return eager_apply("tril", lambda a: jnp.tril(a, int(diagonal)), [x], {})


def triu(x, diagonal=0, name=None) -> Tensor:
    from .dispatch import eager_apply

    return eager_apply("triu", lambda a: jnp.triu(a, int(diagonal)), [x], {})


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    from .dispatch import eager_apply

    def raw(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(int(offset))
            base = jnp.full((n, n), padding_value, a.dtype)
            return base + jnp.diag(a, int(offset)) - jnp.diag(
                jnp.full((a.shape[0],), padding_value, a.dtype), int(offset))
        return jnp.diag(a, int(offset))

    return eager_apply("diag", raw, [x], {})


def diagflat(x, offset=0, name=None) -> Tensor:
    from .dispatch import eager_apply

    return eager_apply("diagflat", lambda a: jnp.diagflat(a, int(offset)), [x], {})


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[t._data for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None) -> Tensor:
    from .dispatch import eager_apply

    src = x if isinstance(x, Tensor) else to_tensor(np.asarray(x))
    out = eager_apply("assign", lambda a: a + 0, [src], {})
    if output is not None:
        output._rebind(out._data, out._grad_node, out._out_idx)
        return output
    return out


def clone(x, name=None) -> Tensor:
    return assign(x)


def numel(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(x.size, jnp.int64))


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), to_jax_dtype(dtype)))


def one_hot(x, num_classes, name=None) -> Tensor:
    from .dispatch import eager_apply

    return eager_apply(
        "one_hot",
        lambda a: jax.nn.one_hot(a, int(num_classes),
                                 dtype=get_default_dtype().np_dtype),
        [x], {})


for _name in __all__:
    _f = globals()[_name]
    if callable(_f) and _name not in ("to_tensor",):
        register_op(_name, _f, tags=("creation",))
register_op("clone", clone, methods=["clone"], tags=("creation",))
