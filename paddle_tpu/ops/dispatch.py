"""Eager op dispatch.

TPU-native equivalent of the reference's generated eager AD functions +
PHI dispatch (reference: the per-op ``*_ad_func`` emitted by
paddle/fluid/eager/auto_code_generator/generator/eager_gen.py and kernel
selection in paddle/phi/api/lib/kernel_dispatch.h:100).

Where the reference's codegen emits, per op, (forward call + GradNode
creation + saved TensorWrappers), we get the same artifact generically:
``eager_apply`` runs the op's functional jnp implementation under
``jax.vjp`` when any input requires grad, records a GradNode with the vjp
closure (JAX traces the backward — the GradNode *is* the saved-tensor
wrapper, closed over immutable arrays), and wires edges to producers.

Ops never hand-write gradients; XLA differentiates the same code that runs
forward, which is the single-source-of-truth property the reference gets
from ops.yaml + backward.yaml.
"""
from __future__ import annotations

import sys
import warnings
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core import engine
from ..core.flags import flag
from ..core.tensor import Tensor
from ..profiler import stats as _stats
from ..profiler.profiler import _SPANS, RecordEvent

__all__ = ["eager_apply", "as_tensor_args", "defun", "inplace_apply"]

# The compiled-forward fast path donates in-place op buffers; CPU jaxlib
# has no donation support and warns per compiled function — silence it
# (donation there is simply a no-op, results are unaffected).
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

# per-op call counters, cached so the hot dispatch path pays one dict
# lookup (not a registry lock) per call; cache outcome counters are
# module-bound for the same reason
_OP_COUNTERS: Dict[str, Any] = {}
_C_HIT = _stats.counter("vjp_cache.hit")
_C_MISS = _stats.counter("vjp_cache.miss")
_C_ADMIT = _stats.counter("vjp_cache.admit")
_C_BLOCKLISTED = _stats.counter("vjp_cache.blocklisted")
_C_BLOCKED = _stats.counter("vjp_cache.blocked")
_C_UNCACHEABLE = _stats.counter("vjp_cache.uncacheable")
_F_HIT = _stats.counter("fwd_cache.hit")
_F_MISS = _stats.counter("fwd_cache.miss")
_F_ADMIT = _stats.counter("fwd_cache.admit")
_F_BLOCKLISTED = _stats.counter("fwd_cache.blocklisted")
_F_BLOCKED = _stats.counter("fwd_cache.blocked")
_F_UNCACHEABLE = _stats.counter("fwd_cache.uncacheable")

#: trace-time errors that mean "this op's python body needs concrete
#: values" — such signatures are blocklisted once and permanently fall
#: back to the plain eager path
_TRACE_ERRS = (jax.errors.JAXTypeError, jax.errors.UnexpectedTracerError)


def _op_counter(op_name: str):
    c = _OP_COUNTERS.get(op_name)
    if c is None:
        c = _OP_COUNTERS[op_name] = _stats.counter("op." + op_name)
    return c


# ---------------------------------------------------------------------------
# Taped-backward vjp cache.
#
# ``jax.vjp`` retraces the op on every tape-recorded call (~750µs/op on the
# tunneled chip — OPBENCH r4), which eager ``backward()`` training pays per
# op per step. The reference amortizes this with codegen'd GradNodes
# (eager_gen.py); we amortize it by jitting the (primals, residuals) forward
# and the residual->cotangent backward once per (op, static kwargs, input
# avals) — the same aval-keyed trick that fixed eager flash-attention
# forwards in r4. Residuals cross the jit boundary as flattened leaves (the
# VJP pytree's treedef is cached host-side; hashing it per call is what made
# the naive "return the VJP object" scheme slow).
#
# Admission: an entry is built only for a ``raw_fn`` OBJECT seen at least
# twice (weakref sighting). Per-call closures — dropout's fresh mask,
# gumbel's noise — get a fresh function object every call, so they are never
# admitted, which is also what makes caching them SAFE to skip: their closed-
# over randomness must not be baked into a compiled trace. Ops whose trace
# needs concrete values (TracerBool/Concretization errors under jit) are
# blocklisted on first failure and permanently fall back to plain jax.vjp.
# ---------------------------------------------------------------------------

class _CachedVJP:
    __slots__ = ("fwd", "bwd", "box", "raw_fn")

    def __init__(self, op_name, raw_fn, static_kwargs, n_args, diff_idx):
        self.raw_fn = raw_fn  # strong ref: pins id() while entry lives
        self.box = box = {}
        const_idx = [i for i in range(n_args) if i not in set(diff_idx)]
        from jax import tree_util as jtu

        def fwd(*arrays):
            cmap = {i: arrays[i] for i in const_idx}

            def f(*diff):
                full = _interleave(cmap, n_args, diff)
                out = raw_fn(*full, **static_kwargs)
                box["was_tuple"] = isinstance(out, tuple)
                return out if isinstance(out, tuple) else (out,)

            primals, vf = jax.vjp(f, *(arrays[i] for i in diff_idx))
            leaves, td = jtu.tree_flatten(vf)
            box["td"], box["n_out"] = td, len(primals)
            box["n_res"] = len(leaves)
            return tuple(primals) + tuple(leaves)

        def bwd(*args):
            vf = jtu.tree_unflatten(box["td"], list(args[:box["n_res"]]))
            return tuple(vf(tuple(args[box["n_res"]:])))

        self.fwd = jax.jit(fwd)
        self.bwd = jax.jit(bwd)


_VJP_CACHE: "OrderedDict[tuple, _CachedVJP]" = OrderedDict()
_VJP_CACHE_MAX = 1024
_VJP_BLOCK: set = set()          # keys whose trace needs concrete values


class _AdmissionTracker:
    """Seen-twice admission discipline, shared by the VJP and the
    compiled-forward caches.

    A cache entry is only built for a signature key whose ``raw_fn``
    OBJECT has been sighted before under the same key. Per-call closures
    (dropout's fresh mask, gumbel's noise) get a fresh function object
    every call, so they are never admitted — which is also what makes
    skipping them SAFE: their closed-over randomness must never be baked
    into a compiled trace. Keying sightings by the FULL signature (not
    just the function) additionally means an op called with a per-step
    varying static scalar never triggers a compile storm: each distinct
    value must be seen twice before anything is traced.

    The value stored is a weakref to ``raw_fn`` whose callback purges the
    entry when the referent dies. This fixes the latent id-reuse bug of
    the old id-keyed dict: without the purge, a recycled ``id()`` could
    inherit a stale sighting and falsely admit a per-call closure.
    """

    __slots__ = ("_seen", "_max")

    def __init__(self, max_entries: int = 8192):
        self._seen: Dict[Any, Any] = {}
        self._max = max_entries

    def admit(self, key, raw_fn) -> bool:
        """True when (key, raw_fn) was already sighted — build the entry
        now. False records the sighting (first time, or a different
        object under the same key)."""
        ref = self._seen.get(key)
        if ref is not None and ref() is raw_fn:
            return True
        if len(self._seen) >= self._max:
            # drop dead refs first; if genuinely full, evict oldest
            dead = [k for k, r in self._seen.items() if r() is None]
            for k in dead:
                self._seen.pop(k, None)
            while len(self._seen) >= self._max:
                self._seen.pop(next(iter(self._seen)), None)
        seen = self._seen

        def _purge(r, _seen=seen, _key=key):
            if _seen.get(_key) is r:
                _seen.pop(_key, None)

        self._seen[key] = weakref.ref(raw_fn, _purge)
        return False

    def clear(self) -> None:
        self._seen.clear()

    def __len__(self) -> int:
        return len(self._seen)


_VJP_SEEN = _AdmissionTracker()   # taped-path sightings
_FWD_SEEN = _AdmissionTracker()   # no-grad-path sightings

_STATIC_OK_TYPES = (str, bytes, int, float, bool, type(None), np.dtype,
                    np.generic)

try:  # slice objects are only hashable from python 3.12
    hash(slice(None))
    _SLICE_HASHABLE = True
except TypeError:
    _SLICE_HASHABLE = False


def _static_ok(v) -> bool:
    """Is a static-kwarg value safe to bake into a compiled trace?
    Conservative allowlist: plain immutable scalars/strings, dtypes,
    (nested) tuples and slices thereof. Tensors/arrays are rejected even
    though they hash by identity — baking their VALUES into a jitted
    executable would silently freeze them."""
    if isinstance(v, _STATIC_OK_TYPES) or isinstance(v, type):
        return True
    if isinstance(v, tuple):
        return all(_static_ok(x) for x in v)
    if isinstance(v, slice):
        return (_SLICE_HASHABLE and _static_ok(v.start)
                and _static_ok(v.stop) and _static_ok(v.step))
    return False


def _sig_key(raw_fn, static_kwargs, arrays, extra):
    """Hashable signature key ``(raw_fn identity, static kwargs, input
    avals incl. weak_type, extra)``, or None when a static kwarg is not
    safely bakeable (arrays, lists, Tensors) — those calls use the plain
    path. ``extra`` discriminates cache flavors (diff_idx for the VJP
    cache, the donation mask for the forward cache)."""
    for v in static_kwargs.values():
        if not _static_ok(v):
            return None
    skey = tuple(sorted(static_kwargs.items()))
    avals = tuple(
        (a.shape, str(a.dtype), bool(getattr(a, "weak_type", False)))
        for a in arrays)
    return (id(raw_fn), skey, avals, extra)


def _vjp_cache_key(raw_fn, static_kwargs, arrays, diff_idx):
    return _sig_key(raw_fn, static_kwargs, arrays, tuple(diff_idx))


def _vjp_cache_admit(key, op_name, raw_fn, static_kwargs, n_args,
                     diff_idx):
    """After a successful uncached call: build an entry on the second
    sighting of the same (key, raw_fn object) pair."""
    if not _VJP_SEEN.admit(key, raw_fn):
        return
    _C_ADMIT.inc()
    with _stats.timed("compile.vjp_build_us"):
        _VJP_CACHE[key] = _CachedVJP(op_name, raw_fn, static_kwargs,
                                     n_args, diff_idx)
    while len(_VJP_CACHE) > _VJP_CACHE_MAX:
        _VJP_CACHE.popitem(last=False)


# ---------------------------------------------------------------------------
# Compiled-forward fast path (no-grad dispatch).
#
# Inference mode, the ContinuousBatchingEngine host loop, and every
# ``no_grad`` region used to pay primitive-by-primitive dispatch for
# composite ops: OPBENCH r05 measured eager ``gelu`` at 378µs vs 24.8µs
# jitted, ``cross_entropy`` 1378.9µs vs 25.5µs. The reference amortizes
# this with codegen'd PHI kernels per op (eager_gen.py +
# kernel_dispatch.h); we amortize it the same way the taped path does —
# a jit-compiled executable per (raw_fn identity, static kwargs, input
# avals), admitted under the shared seen-twice discipline and LRU
# bounded. In-place ops (``*_`` family) additionally DONATE the target
# buffer so steady-state eager inference stops double-buffering; a
# refcount guard skips donation whenever anything else aliases the
# buffer, so the aliasing is never visible to callers.
# ---------------------------------------------------------------------------

_FWD_CACHE: "OrderedDict[tuple, _CachedFwd]" = OrderedDict()
_FWD_CACHE_MAX = 1024
_FWD_BLOCK: set = set()          # keys whose trace needs concrete values


class _CachedFwd:
    __slots__ = ("fn", "box", "raw_fn")

    def __init__(self, raw_fn, static_kwargs, donate):
        self.raw_fn = raw_fn  # strong ref: pins id() while entry lives
        self.box = box = {}

        def call(*arrays):
            out = raw_fn(*arrays, **static_kwargs)
            box["was_tuple"] = isinstance(out, tuple)
            return out if isinstance(out, tuple) else (out,)

        self.fn = jax.jit(call, donate_argnums=donate) if donate \
            else jax.jit(call)


def _donation_safe(arrays, i) -> bool:
    """May ``arrays[i]``'s buffer be donated? Refs visible at this point
    are: the ``arrays`` list, getrefcount's own argument, and — unless
    AMP cast produced a fresh temp — the owning ``Tensor._data``. Any
    count above that is an external alias (``t.detach()``, a saved vjp
    residual, a user variable) whose buffer donation would invalidate."""
    return sys.getrefcount(arrays[i]) <= 3


def _poison_donated(op_name, arrays, eff_donate):
    """FLAGS_check_donation: after a donated dispatch the donated input
    buffers are dead on TPU — register them so any alias that slipped
    the refcount guard fails its next read loudly (CPU jaxlib ignores
    donation, so without this the bug is invisible off-chip)."""
    from ..analysis import donation as _don

    for i in eff_donate:
        _don.poison(arrays[i], op_name)


def _check_poisoned(arrays, reader):
    from ..analysis import donation as _don

    _don.assert_not_poisoned(arrays, reader)


def _forward_fast_path(raw_fn, arrays, static_kwargs, donate_idx,
                       op_name="<op>"):
    """Try the compiled-forward cache for a no-grad dispatch. Returns
    ``(outs, was_tuple)`` when a compiled executable served the call,
    None to fall back to the plain eager path."""
    if not arrays or not flag("eager_fwd_cache"):
        # zero-input programs bake their outputs as constants, which
        # permanently degrades dispatch on the tunneled TPU platform —
        # never cache those
        return None
    eff_donate = ()
    if donate_idx:
        eff_donate = tuple(i for i in donate_idx if _donation_safe(arrays, i))
    key = _sig_key(raw_fn, static_kwargs, arrays, eff_donate)
    if key is None:
        _F_UNCACHEABLE.inc()
        _F_MISS.inc()
        return None
    if key in _FWD_BLOCK:
        _F_BLOCKED.inc()
        _F_MISS.inc()
        return None
    entry = _FWD_CACHE.get(key)
    if entry is not None:
        try:
            outs = entry.fn(*arrays)
        except _TRACE_ERRS:
            _F_BLOCKLISTED.inc()
            _F_MISS.inc()
            _FWD_BLOCK.add(key)
            del _FWD_CACHE[key]
            return None
        _F_HIT.inc()
        _FWD_CACHE.move_to_end(key)
        if eff_donate and flag("check_donation"):
            _poison_donated(op_name, arrays, eff_donate)
        return outs, entry.box.get("was_tuple", False)
    if not _FWD_SEEN.admit(key, raw_fn):
        _F_MISS.inc()
        return None
    entry = _CachedFwd(raw_fn, static_kwargs, eff_donate)
    try:
        with _stats.timed("compile.fwd_trace_us"):
            outs = entry.fn(*arrays)
    except _TRACE_ERRS:
        _F_BLOCKLISTED.inc()
        _F_MISS.inc()
        _FWD_BLOCK.add(key)
        return None
    _F_ADMIT.inc()
    _FWD_CACHE[key] = entry
    while len(_FWD_CACHE) > _FWD_CACHE_MAX:
        _FWD_CACHE.popitem(last=False)
    if eff_donate and flag("check_donation"):
        _poison_donated(op_name, arrays, eff_donate)
    return outs, entry.box.get("was_tuple", False)


def _is_diff_dtype(arr) -> bool:
    return jnp.issubdtype(arr.dtype, jnp.inexact)


def _interleave(const_map, n, diff_arrays):
    """Rebuild the full positional array list from constants + the
    differentiable subset (shared by the forward vjp closure and the
    double-grad replay in engine._apply_node)."""
    full, it = [], iter(diff_arrays)
    for i in range(n):
        full.append(const_map[i] if i in const_map else next(it))
    return full


def as_tensor_args(*args) -> List[Tensor]:
    out = []
    for a in args:
        if isinstance(a, Tensor):
            out.append(a)
        else:
            out.append(Tensor(jnp.asarray(a)))
    return out


def _check_finite(op_name: str, arrays) -> None:
    for a in arrays:
        if jnp.issubdtype(a.dtype, jnp.inexact):
            bad = bool(jnp.any(~jnp.isfinite(a)))
            if bad:
                msg = f"NaN/Inf detected in output of op `{op_name}`"
                if flag("check_nan_inf_level") == 0:
                    raise FloatingPointError(msg)
                print("[check_nan_inf]", msg)


def eager_apply(
    op_name: str,
    raw_fn: Callable,
    tensor_inputs: Sequence[Tensor],
    static_kwargs: Optional[Dict[str, Any]] = None,
    n_outputs: Optional[int] = 1,
    donate_idx: Sequence[int] = (),
):
    """Run one eager op.

    ``raw_fn(*arrays, **static_kwargs)`` is the functional implementation
    over raw jax arrays; ``tensor_inputs`` are the Tensor operands in
    positional order. Returns Tensor or tuple of Tensors (``n_outputs``).
    ``donate_idx`` marks inputs whose buffers MAY be donated to the
    compiled no-grad fast path (the in-place op family — the caller
    rebinds the target afterwards, see ``inplace_apply``); donation is
    skipped whenever the buffer is aliased elsewhere.

    Telemetry: every call bumps the ``op.<name>`` counter
    (profiler.stats); when a profiler window is recording, the whole
    dispatch additionally runs under an auto ``op::<name>`` RecordEvent
    span, so ``Profiler.summary()`` sees per-op count/total/avg/max
    without manual annotation.
    """
    _op_counter(op_name).inc()
    if not _SPANS.enabled:
        return _eager_apply_impl(op_name, raw_fn, tensor_inputs,
                                 static_kwargs, n_outputs, donate_idx)
    ev = RecordEvent("op::" + op_name)
    ev.begin()
    try:
        return _eager_apply_impl(op_name, raw_fn, tensor_inputs,
                                 static_kwargs, n_outputs, donate_idx)
    finally:
        ev.end()


def _eager_apply_impl(
    op_name: str,
    raw_fn: Callable,
    tensor_inputs: Sequence[Tensor],
    static_kwargs: Optional[Dict[str, Any]] = None,
    n_outputs: Optional[int] = 1,
    donate_idx: Sequence[int] = (),
):
    static_kwargs = static_kwargs or {}
    arrays = [t._data for t in tensor_inputs]

    if flag("check_donation"):
        _check_poisoned(arrays, f"op `{op_name}`")

    # AMP O1 autocast (reference: eager_gen.py:515 AMP logic in generated
    # ad_funcs + python/paddle/amp/auto_cast.py lists): white-list ops run in
    # the low-precision dtype, black-list ops in float32.
    from ..amp.auto_cast import _amp_cast_arrays

    arrays = _amp_cast_arrays(op_name, arrays)

    from ..amp.debugging import _op_stats, _record_op

    if _op_stats["enabled"]:
        for a in arrays:
            _record_op(op_name, a.dtype)

    grad_wanted = engine.is_grad_enabled() and any(
        (not t.stop_gradient) and _is_diff_dtype(t._data)
        for t in tensor_inputs
    )

    if not grad_wanted:
        fast = _forward_fast_path(raw_fn, arrays, static_kwargs,
                                  donate_idx, op_name=op_name)
        if fast is not None:
            outs, was_tuple = fast
        else:
            out = raw_fn(*arrays, **static_kwargs)
            was_tuple = isinstance(out, tuple)
            outs = out if was_tuple else (out,)
        if n_outputs is None:  # auto: single unless raw returned a tuple
            n_outputs = len(outs) if was_tuple else 1
        if flag("check_nan_inf"):
            _check_finite(op_name, outs)
        tensors = tuple(Tensor(o) for o in outs)
        _maybe_record(op_name, raw_fn, static_kwargs, tensor_inputs,
                      tensors)
        return tensors if n_outputs != 1 else tensors[0]

    diff_idx = [
        i for i, t in enumerate(tensor_inputs)
        if (not t.stop_gradient) and _is_diff_dtype(t._data)
    ]
    diff_set = set(diff_idx)

    cache_key = _vjp_cache_key(raw_fn, static_kwargs, arrays, diff_idx)
    if cache_key is None:
        _C_UNCACHEABLE.inc()
    elif cache_key in _VJP_BLOCK:
        _C_BLOCKED.inc()
        cache_key = None
    entry = _VJP_CACHE.get(cache_key) if cache_key is not None else None

    primals_out = vjp_fn = None
    if entry is not None:
        try:
            out_flat = entry.fwd(*arrays)
        except (jax.errors.JAXTypeError, jax.errors.UnexpectedTracerError):
            # trace needs concrete values — permanent plain-vjp fallback
            # (cache_key cleared so the fallback below can't re-admit a
            # zombie entry under the blocked key)
            _C_BLOCKLISTED.inc()
            _VJP_BLOCK.add(cache_key)
            del _VJP_CACHE[cache_key]
            cache_key = None
        else:
            _C_HIT.inc()
            box = entry.box
            primals_out = out_flat[:box["n_out"]]
            res_leaves = out_flat[box["n_out"]:]
            bwd = entry.bwd
            vjp_fn = lambda cots, _b=bwd, _r=res_leaves: _b(*_r, *cots)
            if n_outputs is None:
                n_outputs = box["n_out"] if box["was_tuple"] else 1

    if primals_out is None:
        const_arrays = {i: a for i, a in enumerate(arrays)
                        if i not in diff_set}
        was_tuple = [False]

        def f(*diff_arrays):
            full = _interleave(const_arrays, len(arrays), diff_arrays)
            out = raw_fn(*full, **static_kwargs)
            was_tuple[0] = isinstance(out, tuple)
            return out if isinstance(out, tuple) else (out,)

        _C_MISS.inc()
        with _stats.timed("compile.vjp_trace_us"):
            primals_out, vjp_fn = jax.vjp(f, *[arrays[i] for i in diff_idx])
        if n_outputs is None:  # auto: single unless raw returned a tuple
            n_outputs = len(primals_out) if was_tuple[0] else 1
        if cache_key is not None:
            _vjp_cache_admit(cache_key, op_name, raw_fn, static_kwargs,
                             len(arrays), diff_idx)

    if flag("check_nan_inf"):
        _check_finite(op_name, primals_out)

    edges = []
    for i in diff_idx:
        t = tensor_inputs[i]
        if t._grad_node is not None:
            edges.append(("node", t._grad_node, t._out_idx))
        else:
            edges.append(("leaf", t))

    out_avals = [(o.shape, o.dtype) for o in primals_out]
    node = engine.GradNode(op_name, vjp_fn, edges, out_avals)
    # double-grad support: keep the primal recipe so create_graph can
    # re-express this backward as a differentiable op (engine._apply_node).
    # The recipe bakes in the dtypes the forward actually ran with (AMP
    # may have cast them, and may be OFF at backward time), so the replay
    # reproduces the same out_avals. Recording holds refs to ALL primal
    # inputs (the vjp residuals usually hold most of them anyway);
    # memory-critical first-order-only runs can turn it off via
    # FLAGS_record_double_grad (create_graph then raises).
    if flag("record_double_grad"):
        cast_dtypes = [a.dtype for a in arrays]

        def recipe_fn(*full):
            full = [x.astype(dt) if x.dtype != dt else x
                    for x, dt in zip(full, cast_dtypes)]
            out = raw_fn(*full, **static_kwargs)
            return out if isinstance(out, tuple) else (out,)

        node.second = (recipe_fn, list(tensor_inputs), diff_idx)

    tensors = []
    for idx, o in enumerate(primals_out):
        t = Tensor(o, stop_gradient=not _is_diff_dtype(o))
        t._grad_node = node
        t._out_idx = idx
        tensors.append(t)
    tensors = tuple(tensors)
    _maybe_record(op_name, raw_fn, static_kwargs, tensor_inputs, tensors)
    return tensors if n_outputs != 1 else tensors[0]


_STATIC_STATE = None


def _maybe_record(op_name, raw_fn, static_kwargs, tensor_inputs, tensors):
    """Static-graph recording hook: under static.program_guard every
    dispatched op is appended to the active Program (the ProgramDesc
    build step of the reference's static mode — base/framework.py
    append_op); eager execution proceeds unchanged. The thread-local is
    cached after first use so the common no-guard case costs one
    attribute check per dispatch."""
    global _STATIC_STATE
    if _STATIC_STATE is None:
        from ..static.program import _STATE as _STATIC_STATE_MOD

        _STATIC_STATE = _STATIC_STATE_MOD
    prog = _STATIC_STATE.main
    if prog is not None:
        prog.record(op_name, raw_fn, static_kwargs, tensor_inputs, tensors)


def inplace_apply(
    op_name: str,
    raw_fn: Callable,
    tensor_inputs: Sequence[Tensor],
    static_kwargs: Optional[Dict[str, Any]] = None,
):
    """Dispatch one in-place op: functional ``raw_fn`` + Tensor rebind.

    The target (``tensor_inputs[0]``) is offered for buffer DONATION to
    the compiled-forward fast path: in no-grad steady state the update
    happens in place in HBM instead of double-buffering. Donation is
    skipped (automatically, per call) when the buffer is aliased by
    anything else — ``detach()`` views, saved residuals, a user-held
    array — so the aliasing contract of the ``*_`` family is preserved:
    the caller-visible result is always bit-identical to the undonated
    out-of-place op. Under grad, tapes exactly like the functional op.
    """
    target = tensor_inputs[0]
    out = eager_apply(op_name, raw_fn, tensor_inputs, static_kwargs, 1,
                      donate_idx=(0,))
    target._rebind(out._data, out._grad_node, out._out_idx)
    return target


def defun(op_name: str, n_tensor_args: int = 1, n_outputs: int = 1):
    """Turn a raw-array function into an eager op.

    The first ``n_tensor_args`` positional args are Tensors (scalars are
    promoted); everything keyword is static. ``n_tensor_args=-1`` means all
    positional args are tensors. The raw function stays reachable as
    ``op.raw_fn`` (in-place wrappers re-dispatch it with donation).
    """

    def deco(raw_fn):
        import functools

        @functools.wraps(raw_fn)
        def op(*args, **kwargs):
            nt = len(args) if n_tensor_args < 0 else n_tensor_args
            tensors = as_tensor_args(*args[:nt])
            static = dict(kwargs)
            if nt < len(args):
                raise TypeError(
                    f"{op_name}: extra positional args beyond tensor slots; "
                    "pass them as keywords")
            return eager_apply(op_name, raw_fn, tensors, static, n_outputs)

        op.__name__ = op_name
        op.raw_fn = raw_fn
        return op

    return deco
