"""Long-tail ops from the reference's ops.yaml surface.

Fills the genuinely-missing tail found by tools/op_audit.py (reference:
paddle/phi/api/yaml/ops.yaml entries add_n, bincount, diagonal,
diag_embed, kron, complex, clip_by_norm, logit, nanmedian, mode, renorm,
logcumsumexp, nextafter, polygamma, i0e, i1e, gather_tree,
edit_distance, squared_l2_norm, shard_index, temporal_shift,
fill_diagonal, truncated_gaussian_random). Pure jnp bodies dispatched
through the standard eager path — each is one fused XLA computation.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import eager_apply
from .registry import register_op

__all__ = [
    "add_n", "bincount", "diagonal", "diag_embed", "kron", "complex",
    "clip_by_norm", "logit", "nanmedian", "mode", "renorm",
    "logcumsumexp", "nextafter", "polygamma", "i0e", "i1e",
    "gather_tree", "edit_distance", "squared_l2_norm", "shard_index",
    "temporal_shift", "fill_diagonal", "truncated_normal",
]


def _export(name, fn, methods=(), differentiable=True):
    register_op(name, fn, methods=methods, differentiable=differentiable,
                tags=("extras",))
    return fn


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def add_n(inputs, name=None):
    """(ops.yaml add_n) Elementwise sum of a tensor list."""
    ts = [_t(x) for x in (inputs if isinstance(inputs, (list, tuple))
                          else [inputs])]
    return eager_apply("add_n",
                       lambda *xs: sum(xs[1:], xs[0]), ts)


def bincount(x, weights=None, minlength=0, name=None):
    ts = [_t(x)] + ([_t(weights)] if weights is not None else [])
    n = int(jnp.max(_t(x)._data)) + 1 if _t(x)._data.size else 0
    length = max(n, int(minlength))

    def raw(ids, *w):
        return jnp.bincount(ids.astype(jnp.int32),
                            weights=w[0] if w else None, length=length)

    return eager_apply("bincount", raw, ts)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return eager_apply(
        "diagonal",
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                               axis2=axis2), [_t(x)])


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def raw(a):
        k = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (k, k), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(a)
        dims = list(range(out.ndim))
        d1 = dim1 % out.ndim
        d2 = dim2 % out.ndim
        src1, src2 = out.ndim - 2, out.ndim - 1
        perm = [d for d in dims if d not in (src1, src2)]
        order = []
        it = iter(perm)
        for d in range(out.ndim):
            if d == d1:
                order.append(src1)
            elif d == d2:
                order.append(src2)
            else:
                order.append(next(it))
        return jnp.transpose(out, order)

    return eager_apply("diag_embed", raw, [_t(input)])


def kron(x, y, name=None):
    return eager_apply("kron", jnp.kron, [_t(x), _t(y)])


def complex(real, imag, name=None):
    return eager_apply("complex", jax.lax.complex, [_t(real), _t(imag)])


def clip_by_norm(x, max_norm, name=None):
    def raw(a):
        n = jnp.sqrt(jnp.sum(jnp.square(a)))
        return jnp.where(n > max_norm, a * (max_norm / n), a)

    return eager_apply("clip_by_norm", raw, [_t(x)])


def logit(x, eps=None, name=None):
    def raw(a):
        p = a if eps is None else jnp.clip(a, eps, 1 - eps)
        return jnp.log(p) - jnp.log1p(-p)

    return eager_apply("logit", raw, [_t(x)])


def nanmedian(x, axis=None, keepdim=False, name=None):
    return eager_apply(
        "nanmedian",
        lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), [_t(x)])


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis (ops.yaml mode): returns
    (values, indices); ties resolve to the smallest value, index is its
    last occurrence (paddle kernel semantics)."""
    def raw(a):
        sorted_a = jnp.sort(a, axis=axis)
        moved = jnp.moveaxis(sorted_a, axis, -1)
        n = moved.shape[-1]
        runs = jnp.cumsum(
            jnp.concatenate([jnp.ones(moved.shape[:-1] + (1,), jnp.int32),
                             (moved[..., 1:] != moved[..., :-1])
                             .astype(jnp.int32)], -1), -1)
        # count of each element's run, take the value with max run len
        counts = jax.vmap(lambda r: jnp.bincount(r, length=n + 1),
                          in_axes=0)(runs.reshape(-1, n))
        counts = counts.reshape(runs.shape[:-1] + (n + 1,))
        best_run = jnp.argmax(counts, -1)
        # last element of the best run
        pos = jnp.sum((runs <= best_run[..., None]).astype(jnp.int32),
                      -1) - 1
        vals = jnp.take_along_axis(moved, pos[..., None], -1)[..., 0]
        orig = jnp.moveaxis(a, axis, -1)
        match = orig == vals[..., None]
        idx = (n - 1) - jnp.argmax(jnp.flip(match, -1), -1)
        if keepdim:
            vals, idx = vals[..., None], idx[..., None]
            vals = jnp.moveaxis(vals, -1, axis)
            idx = jnp.moveaxis(idx, -1, axis)
        return vals, idx.astype(jnp.int64)

    return eager_apply("mode", raw, [_t(x)], n_outputs=2)


def renorm(x, p, axis, max_norm, name=None):
    def raw(a):
        dims = tuple(d for d in range(a.ndim) if d != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims,
                        keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7),
                           1.0)
        return a * factor

    return eager_apply("renorm", raw, [_t(x)])


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def raw(a):
        b = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, b, axis=ax)

    return eager_apply("logcumsumexp", raw, [_t(x)])


def nextafter(x, y, name=None):
    return eager_apply("nextafter", jnp.nextafter, [_t(x), _t(y)],
                       )


def polygamma(x, n, name=None):
    import jax.scipy.special as jsp

    return eager_apply("polygamma",
                       lambda a: jsp.polygamma(n, a), [_t(x)])


def i0e(x, name=None):
    import jax.scipy.special as jsp

    return eager_apply("i0e", jsp.i0e, [_t(x)])


def i1e(x, name=None):
    import jax.scipy.special as jsp

    return eager_apply("i1e", jsp.i1e, [_t(x)])


def squared_l2_norm(x, name=None):
    return eager_apply("squared_l2_norm",
                       lambda a: jnp.sum(jnp.square(a)).reshape(1),
                       [_t(x)])


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (ops.yaml gather_tree): ids/parents
    [max_time, batch, beam] -> full predicted sequences."""
    def raw(ids_, par):
        T = ids_.shape[0]

        def step(carry, t):
            beams = carry  # [batch, beam] current beam ids
            out_t = jnp.take_along_axis(ids_[t], beams, axis=1)
            nxt = jnp.take_along_axis(par[t], beams, axis=1)
            return nxt, out_t

        init = jnp.broadcast_to(jnp.arange(ids_.shape[2]),
                                ids_.shape[1:]).astype(ids_.dtype)
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(outs, axis=0)

    return eager_apply("gather_tree", raw, [_t(ids), _t(parents)])


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance per pair (ops.yaml edit_distance). Host
    computation (non-differentiable, ragged)."""
    hyp = np.asarray(_t(input)._data)
    ref = np.asarray(_t(label)._data)
    hl = np.asarray(_t(input_length)._data) if input_length is not None \
        else np.full(hyp.shape[0], hyp.shape[1])
    rl = np.asarray(_t(label_length)._data) if label_length is not None \
        else np.full(ref.shape[0], ref.shape[1])
    out = np.zeros((hyp.shape[0], 1), np.float32)
    seq_num = np.array([hyp.shape[0]], np.int64)
    for i in range(hyp.shape[0]):
        a = [t for t in hyp[i, : int(hl[i])].tolist()
             if not ignored_tokens or t not in ignored_tokens]
        b = [t for t in ref[i, : int(rl[i])].tolist()
             if not ignored_tokens or t not in ignored_tokens]
        dp = np.arange(len(b) + 1, dtype=np.float32)
        for x_tok in a:
            prev = dp.copy()
            dp[0] = prev[0] + 1
            for j, y_tok in enumerate(b, 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (x_tok != y_tok))
        d = dp[-1]
        if normalized:
            d = d / max(len(b), 1)
        out[i, 0] = d
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(seq_num))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """(ops.yaml shard_index) Recode global ids into a shard's local id
    space; out-of-shard ids map to ignore_value."""
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range [0, {nshards})")
    shard_size = (index_num + nshards - 1) // nshards

    def raw(ids):
        in_shard = (ids // shard_size) == shard_id
        return jnp.where(in_shard, ids % shard_size, ignore_value)

    return eager_apply("shard_index", raw, [_t(input)])


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """(ops.yaml temporal_shift) TSM channel shift across time segments."""
    def raw(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate(
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], 1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold:2 * fold]),
             v[:, :-1, fold:2 * fold]], 1)
        keep = v[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep], 2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return eager_apply("temporal_shift", raw, [_t(x)])


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    if wrap:
        raise NotImplementedError(
            "fill_diagonal(wrap=True) (tall-matrix diagonal wrapping) "
            "is not supported")

    def raw(a):
        rows, cols = a.shape[-2], a.shape[-1]
        # true length of the offset diagonal of a possibly non-square
        # matrix
        n = min(rows + min(offset, 0), cols - max(offset, 0))
        idx = jnp.arange(max(n, 0))
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        return a.at[..., r, c].set(value)

    return eager_apply("fill_diagonal", raw, [_t(x)])


def truncated_normal(shape, mean=0.0, std=1.0, dtype=None, a=-2.0,
                     b=2.0, name=None):
    """(ops.yaml truncated_gaussian_random) 2-sigma truncated normal."""
    from ..core.generator import next_rng_key

    dt = jnp.float32 if dtype is None else dtype
    z = jax.random.truncated_normal(next_rng_key(), a, b, tuple(shape),
                                    jnp.float32)
    return Tensor((mean + std * z).astype(dt))


for _name, _fn, _methods in [
    ("add_n", add_n, ()),
    ("bincount", bincount, ("bincount",)),
    ("diagonal", diagonal, ("diagonal",)),
    ("diag_embed", diag_embed, ()),
    ("kron", kron, ("kron",)),
    ("complex", complex, ()),
    ("clip_by_norm", clip_by_norm, ()),
    ("logit", logit, ("logit",)),
    ("nanmedian", nanmedian, ("nanmedian",)),
    ("mode", mode, ("mode",)),
    ("renorm", renorm, ()),
    ("logcumsumexp", logcumsumexp, ("logcumsumexp",)),
    ("nextafter", nextafter, ()),
    ("polygamma", polygamma, ()),
    ("i0e", i0e, ()),
    ("i1e", i1e, ()),
    ("gather_tree", gather_tree, ()),
    ("squared_l2_norm", squared_l2_norm, ()),
    ("shard_index", shard_index, ()),
    ("temporal_shift", temporal_shift, ()),
    ("fill_diagonal", fill_diagonal, ()),
]:
    _export(_name, _fn, methods=_methods)
_export("edit_distance", edit_distance, differentiable=False)
_export("truncated_normal", truncated_normal, differentiable=False)
