"""__getitem__ / __setitem__.

Reference: the eager tensor indexing in paddle/fluid/pybind/
eager_method.cc (`__getitem__` slicing + advanced indexing) and
python/paddle/base/variable_index.py. Basic indexing lowers to static XLA
slices; integer-tensor indexing to gathers; boolean-mask reads are
dynamic-shape and therefore eager-only (host roundtrip), while boolean
mask *writes* stay compiled via ``where``.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import eager_apply

__all__ = []


def _parse(index):
    """Split index into (static_part, tensor_arrays). Tensor indices are
    replaced by sentinels resolved inside the closure."""
    if not isinstance(index, tuple):
        index = (index,)
    out = []
    for it in index:
        if isinstance(it, Tensor):
            d = it._data
            if d.dtype == jnp.bool_:
                out.append(np.asarray(d))  # dynamic: host materialize
            else:
                out.append(d)
        elif isinstance(it, (list, np.ndarray)):
            arr = np.asarray(it)
            out.append(arr)
        else:
            out.append(it)
    return tuple(out)


def _getitem(self: Tensor, index):
    idx = _parse(index)
    has_bool = any(isinstance(i, np.ndarray) and i.dtype == np.bool_
                   for i in idx)
    if has_bool:
        # dynamic result shape: eager-only host path
        return Tensor(jnp.asarray(np.asarray(self._data)[idx]))
    return eager_apply("getitem", lambda a: a[idx], [self], {})


def _setitem(self: Tensor, index, value):
    idx = _parse(index)
    if isinstance(value, Tensor):
        out = eager_apply(
            "setitem",
            lambda a, v: a.at[idx].set(v.astype(a.dtype)), [self, value], {})
    else:
        out = eager_apply(
            "setitem", lambda a: a.at[idx].set(value), [self], {})
    self._rebind(out._data, out._grad_node, out._out_idx)
    return self


Tensor._attach_method("__getitem__", _getitem)
Tensor._attach_method("__setitem__", _setitem)
