"""Linear algebra ops.

Reference surface: python/paddle/tensor/linalg.py (matmul at :245 →
_C_ops.matmul) and paddle/phi/kernels gpu matmul/blas kernels. On TPU the
matmul family lowers straight onto the MXU; ``FLAGS_use_bf16_matmul``
keeps inputs in bf16 with f32 accumulation via ``preferred_element_type``
— the idiomatic XLA way to hit MXU peak.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import eager_apply
from .registry import register_op

__all__: list = []


def _export(name, fn, methods=(), differentiable=True):
    globals()[name] = fn
    __all__.append(name)
    register_op(name, fn, methods=methods, differentiable=differentiable,
                tags=("linalg",))
    return fn


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _matmul_raw(a, b, transpose_x=False, transpose_y=False):
    if transpose_x:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_y:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    # f32 accumulation for low-precision inputs: MXU-native
    if a.dtype in (jnp.bfloat16, jnp.float16):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    return jnp.matmul(a, b)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return eager_apply("matmul", _matmul_raw, [_as_tensor(x), _as_tensor(y)],
                       {"transpose_x": bool(transpose_x),
                        "transpose_y": bool(transpose_y)})


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return eager_apply("mv", lambda a, b: a @ b, [x, vec], {})


def dot(x, y, name=None):
    return eager_apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), [x, y], {})


def t(input, name=None):
    def raw(a):
        return a.T if a.ndim >= 2 else a

    return eager_apply("t", raw, [input], {})


Tensor._attach_method("__matmul__", lambda self, other: matmul(self, other))
Tensor._attach_method("__rmatmul__", lambda self, other: matmul(other, self))

for _n in ("matmul", "mm", "bmm", "mv", "dot", "t"):
    _export(_n, globals()[_n], methods=[_n])


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = _as_tensor(x)

    def raw(a):
        if axis is None and p is None:
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a))))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        pp = 2 if p is None or p == "fro" else p
        if pp == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pp == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pp == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        if pp == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a)), axis=ax,
                                    keepdims=keepdim))
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(a), pp), axis=ax, keepdims=keepdim),
            1.0 / pp)

    return eager_apply("norm", raw, [x], {})


def dist(x, y, p=2, name=None):
    return norm(x - y, p=float(p))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    a = np.asarray(_as_tensor(input)._data)
    lo, hi = (a.min(), a.max()) if min == 0 and max == 0 else (min, max)
    h, _ = np.histogram(a, bins=int(bins), range=(float(lo), float(hi)),
                        weights=None if weight is None else np.asarray(weight._data),
                        density=density)
    return Tensor(jnp.asarray(h if density else h.astype(np.int64)))


def cross(x, y, axis=9, name=None):
    x = _as_tensor(x)
    ax = axis if axis != 9 else next(
        i for i, s in enumerate(x.shape) if s == 3)
    return eager_apply("cross",
                       lambda a, b: jnp.cross(a, b, axis=int(ax)),
                       [x, _as_tensor(y)], {})


for _n in ("norm", "dist", "histogram", "cross"):
    _export(_n, globals()[_n], methods=[_n],
            differentiable=_n != "histogram")


# ---------------------------------------------------- decompositions
def cholesky(x, upper=False, name=None):
    def raw(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return eager_apply("cholesky", raw, [x], {})


def cholesky_solve(x, y, upper=False, name=None):
    def raw(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return eager_apply("cholesky_solve", raw, [x, y], {})


def qr(x, mode="reduced", name=None):
    outs = eager_apply("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)),
                       [x], {}, n_outputs=2)
    return outs


def svd(x, full_matrices=False, name=None):
    return eager_apply(
        "svd",
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        [x], {}, n_outputs=3)


def eig(x, name=None):
    a = np.asarray(_as_tensor(x)._data)
    w, v = np.linalg.eig(a)
    # Tensor() places complex results on the CPU device (no TPU support)
    return Tensor(w), Tensor(v)


def _from_triangle(a, UPLO):
    """Hermitian matrix from ONE triangle (LAPACK UPLO semantics: the
    other triangle's contents are ignored; off-diagonal mirror is
    CONJUGATED for complex inputs)."""
    diag = jnp.triu(jnp.tril(a))
    tri = jnp.triu(a) if UPLO == "U" else jnp.tril(a)
    return tri + jnp.conj(jnp.swapaxes(tri, -1, -2)) - diag


def eigh(x, UPLO="L", name=None):
    t = _as_tensor(x)
    if jnp.issubdtype(t._data.dtype, jnp.complexfloating):
        # complex is unsupported on the TPU backend: host path (same
        # treatment as eig)
        a = np.asarray(t._data)
        tri = np.triu(a) if UPLO == "U" else np.tril(a)
        herm = tri + np.conj(tri.swapaxes(-1, -2)) - np.triu(np.tril(a))
        w, v = np.linalg.eigh(herm)
        return Tensor(w), Tensor(v)
    return eager_apply(
        "eigh",
        lambda a: tuple(jnp.linalg.eigh(_from_triangle(a, UPLO),
                                        symmetrize_input=False)),
        [x], {}, n_outputs=2)


def eigvals(x, name=None):
    a = np.asarray(_as_tensor(x)._data)
    return Tensor(np.linalg.eigvals(a))


def eigvalsh(x, UPLO="L", name=None):
    t = _as_tensor(x)
    if jnp.issubdtype(t._data.dtype, jnp.complexfloating):
        w, _ = eigh(t, UPLO=UPLO)
        return w
    return eager_apply(
        "eigvalsh",
        lambda a: jnp.linalg.eigvalsh(_from_triangle(a, UPLO)), [x], {})


def inverse(x, name=None):
    return eager_apply("inverse", lambda a: jnp.linalg.inv(a), [x], {})


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return eager_apply(
        "pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
        [x], {})


def solve(x, y, name=None):
    def raw(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)

    return eager_apply("solve", raw, [x, y], {})


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def raw(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)

    return eager_apply("triangular_solve", raw, [x, y], {})


def lstsq(x, y, rcond=None, driver=None, name=None):
    # jnp path: the solution is differentiable through the tape
    return eager_apply(
        "lstsq",
        lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
        [x, y], {}, n_outputs=4)


def det(x, name=None):
    return eager_apply("det", lambda a: jnp.linalg.det(a), [x], {})


def slogdet(x, name=None):
    def raw(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet], axis=0)

    return eager_apply("slogdet", raw, [x], {})


def matrix_power(x, n, name=None):
    return eager_apply("matrix_power",
                       lambda a: jnp.linalg.matrix_power(a, int(n)), [x], {})


def matrix_rank(x, tol=None, hermitian=False, name=None):
    a = _as_tensor(x)._data
    if tol is None:
        return Tensor(jnp.linalg.matrix_rank(a).astype(jnp.int64))
    # Paddle's tol is an ABSOLUTE singular-value threshold
    s = jnp.abs(jnp.linalg.eigvalsh(a)) if hermitian else \
        jnp.linalg.svd(a, compute_uv=False)
    return Tensor(jnp.sum(s > tol, axis=-1).astype(jnp.int64))


def cond(x, p=None, name=None):
    return Tensor(jnp.linalg.cond(_as_tensor(x)._data, p=p))


def multi_dot(x, name=None):
    return eager_apply("multi_dot",
                       lambda *arrs: jnp.linalg.multi_dot(arrs), list(x), {})


def lu(x, pivot=True, get_infos=False, name=None):
    def raw(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv

    a = _as_tensor(x)._data
    lu_, piv = jax.scipy.linalg.lu_factor(a)
    outs = (Tensor(lu_), Tensor((piv + 1).astype(jnp.int32)))
    if get_infos:
        return outs + (Tensor(jnp.zeros((), jnp.int32)),)
    return outs


def tensordot(x, y, axes=2, name=None):
    def _norm_axes(ax):
        if isinstance(ax, Tensor):
            ax = ax.tolist()
        if isinstance(ax, (list, tuple)):
            return tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                         for a in ax)
        return int(ax)

    return eager_apply("tensordot",
                       lambda a, b: jnp.tensordot(a, b, axes=_norm_axes(axes)),
                       [x, y], {})


def corrcoef(x, rowvar=True, name=None):
    return eager_apply("corrcoef",
                       lambda a: jnp.corrcoef(a, rowvar=rowvar), [x], {})


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return eager_apply(
        "cov",
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), [x], {})


for _n in ("cholesky", "cholesky_solve", "qr", "svd", "eig", "eigh",
           "eigvals", "eigvalsh", "inverse", "pinv", "solve",
           "triangular_solve", "lstsq", "det", "slogdet", "matrix_power",
           "matrix_rank", "cond", "multi_dot", "lu", "tensordot",
           "corrcoef", "cov"):
    _export(_n, globals()[_n], methods=[_n],
            differentiable=_n not in ("eig", "eigvals", "lstsq",
                                      "matrix_rank", "lu"))


def einsum(equation, *operands):
    tensors = [_as_tensor(o) for o in operands]
    return eager_apply("einsum",
                       lambda *arrs: jnp.einsum(equation, *arrs),
                       tensors, {})


_export("einsum", einsum)
