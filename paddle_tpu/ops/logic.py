"""Comparison / logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import eager_apply
from .registry import register_op

__all__: list = []


def _export(name, fn, methods=()):
    globals()[name] = fn
    __all__.append(name)
    register_op(name, fn, methods=methods, differentiable=False,
                tags=("logic",))
    return fn


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _make_cmp(name, jfn, methods):
    def op(x, y=None, name=None, _jfn=jfn):
        if y is None:  # unary (isnan etc.)
            return Tensor(_jfn(_as_tensor(x)._data))
        xa = _as_tensor(x)._data if isinstance(x, Tensor) else x
        ya = _as_tensor(y)._data if isinstance(y, Tensor) else y
        return Tensor(_jfn(xa, ya))

    op.__name__ = name
    return _export(name, op, methods)


_make_cmp("equal", jnp.equal, ["equal", "__eq__"])
_make_cmp("not_equal", jnp.not_equal, ["not_equal", "__ne__"])
_make_cmp("less_than", jnp.less, ["less_than", "__lt__"])
_make_cmp("less_equal", jnp.less_equal, ["less_equal", "__le__"])
_make_cmp("greater_than", jnp.greater, ["greater_than", "__gt__"])
_make_cmp("greater_equal", jnp.greater_equal, ["greater_equal", "__ge__"])
_make_cmp("logical_and", jnp.logical_and, ["logical_and", "__and__"])
_make_cmp("logical_or", jnp.logical_or, ["logical_or", "__or__"])
_make_cmp("logical_xor", jnp.logical_xor, ["logical_xor", "__xor__"])


def logical_not(x, out=None, name=None):
    return Tensor(jnp.logical_not(_as_tensor(x)._data))


_export("logical_not", logical_not, ["logical_not", "__invert__"])


def _make_unary_pred(name, jfn, methods):
    def op(x, name=None, _jfn=jfn):
        return Tensor(_jfn(_as_tensor(x)._data))

    op.__name__ = name
    return _export(name, op, methods)


_make_unary_pred("isnan", jnp.isnan, ["isnan"])
_make_unary_pred("isinf", jnp.isinf, ["isinf"])
_make_unary_pred("isfinite", jnp.isfinite, ["isfinite"])
_make_unary_pred("isneginf", jnp.isneginf, ["isneginf"])
_make_unary_pred("isposinf", jnp.isposinf, ["isposinf"])
_make_unary_pred("isreal", jnp.isreal, ["isreal"])


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(_as_tensor(x)._data, _as_tensor(y)._data,
                               rtol=float(rtol), atol=float(atol),
                               equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(_as_tensor(x)._data, _as_tensor(y)._data,
                              rtol=float(rtol), atol=float(atol),
                              equal_nan=equal_nan))


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(_as_tensor(x)._data, _as_tensor(y)._data))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


for _n in ("allclose", "isclose", "equal_all", "is_empty"):
    _export(_n, globals()[_n], [_n])
_export("is_tensor", is_tensor)


# bitwise family
def _make_bitwise(name, jfn, methods):
    def op(x, y=None, out=None, name=None, _jfn=jfn):
        xa = _as_tensor(x)._data
        if y is None:
            return Tensor(_jfn(xa))
        ya = _as_tensor(y)._data if isinstance(y, Tensor) else y
        return Tensor(_jfn(xa, ya))

    op.__name__ = name
    return _export(name, op, methods)


_make_bitwise("bitwise_and", jnp.bitwise_and, ["bitwise_and"])
_make_bitwise("bitwise_or", jnp.bitwise_or, ["bitwise_or"])
_make_bitwise("bitwise_xor", jnp.bitwise_xor, ["bitwise_xor"])
_make_bitwise("bitwise_not", jnp.bitwise_not, ["bitwise_not"])
_make_bitwise("bitwise_left_shift", jnp.left_shift, ["bitwise_left_shift"])
_make_bitwise("bitwise_right_shift", jnp.right_shift, ["bitwise_right_shift"])


# where lives logically with search ops but is differentiable
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .manipulation import nonzero

        return nonzero(condition, as_tuple=True)
    cond_t = _as_tensor(condition)
    cond = cond_t._data

    xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
    if xt and yt:
        # condition as a (bool, non-diff) tensor input keeps the raw fn
        # stable — admissible to the dispatch caches
        return eager_apply("where", _where_raw, [cond_t, x, y], {})
    if xt:
        return eager_apply("where", _where_scalar_y_raw, [cond_t, x],
                           {"y": y})
    if yt:
        return eager_apply("where", _where_scalar_x_raw, [cond_t, y],
                           {"x": x})
    return Tensor(jnp.where(cond, x, y))


def _where_raw(c, a, b):
    return jnp.where(c, a, b)


def _where_scalar_y_raw(c, a, y=0):
    return jnp.where(c, a, y)


def _where_scalar_x_raw(c, b, x=0):
    return jnp.where(c, x, b)


globals()["where"] = where
__all__.append("where")
register_op("where", where, methods=["where"], tags=("search",))
