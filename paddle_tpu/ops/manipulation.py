"""Shape/layout manipulation ops.

Reference surface: python/paddle/tensor/manipulation.py (+ phi kernels
cpu/gpu concat, split, gather, scatter, stride/ view kernels).

Note on dynamic shapes: ``nonzero``/``masked_select``/``unique`` have
data-dependent output shapes, which XLA cannot compile statically; they
are eager-only here (documented), matching §7.2 of the build plan —
jit-path code should use ``where``/masking instead.
"""
from __future__ import annotations

import builtins

from typing import List, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype, to_jax_dtype
from ..core.tensor import Tensor
from .dispatch import eager_apply
from .registry import register_op

__all__: list = []


def _export(name, fn, methods=(), differentiable=True):
    globals()[name] = fn
    __all__.append(name)
    register_op(name, fn, methods=methods, differentiable=differentiable,
                tags=("manipulation",))
    return fn


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _ints(seq):
    if isinstance(seq, Tensor):
        seq = seq.tolist()
    if isinstance(seq, (int, np.integer)):
        return int(seq)
    return [int(s.item() if isinstance(s, Tensor) else s) for s in seq]


# ------------------------------------------------------------- reshape
def _reshape_raw(a, shape=()):
    return jnp.reshape(a, shape)


def reshape(x, shape, name=None):
    return eager_apply("reshape", _reshape_raw, [x],
                       {"shape": tuple(_ints(shape))})


def reshape_(x, shape, name=None):
    from .dispatch import inplace_apply

    return inplace_apply("reshape", _reshape_raw, [x],
                         {"shape": tuple(_ints(shape))})


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return eager_apply("view_dtype",
                       lambda a: a.view(to_jax_dtype(shape_or_dtype)), [x], {})


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _as_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    new_shape = x.shape[:s] + [-1] + x.shape[e + 1:]
    return reshape(x, new_shape)


def squeeze(x, axis=None, name=None):
    x = _as_tensor(x)
    if axis is None:
        ax = None
    else:
        ax = _ints(axis)
        if isinstance(ax, int):
            ax = [ax]
        ax = tuple(a % x.ndim for a in ax if x.shape[a % x.ndim] == 1)
    return eager_apply("squeeze", lambda a: jnp.squeeze(a, ax), [x], {})


def unsqueeze(x, axis, name=None):
    ax = _ints(axis)
    if isinstance(ax, int):
        ax = [ax]
    return eager_apply("unsqueeze",
                       lambda a: jnp.expand_dims(a, tuple(ax)), [x], {})


for _n, _f in (("reshape", reshape), ("view", view),
               ("flatten", flatten), ("squeeze", squeeze),
               ("unsqueeze", unsqueeze)):
    _export(_n, _f, methods=[_n])

# reshape_ dispatches through inplace_apply, so its registration must
# carry the donation contract (tpu_lint donation audit D-UNDECLARED)
globals()["reshape_"] = reshape_
__all__.append("reshape_")
register_op("reshape_", reshape_, methods=["reshape_"],
            inplace_of="reshape", donates=(0,),
            tags=("manipulation", "inplace"))


def _transpose_raw(a, perm=()):
    return jnp.transpose(a, perm)


def transpose(x, perm=None, name=None):
    x = _as_tensor(x)
    if perm is None:
        perm = list(range(x.ndim))[::-1]
    return eager_apply("transpose", _transpose_raw, [x],
                       {"perm": tuple(_ints(perm))})


def moveaxis(x, source, destination, name=None):
    return eager_apply("moveaxis",
                       lambda a: jnp.moveaxis(a, _ints(source),
                                              _ints(destination)), [x], {})


def swapaxes(x, axis0, axis1, name=None):
    return eager_apply("swapaxes",
                       lambda a: jnp.swapaxes(a, int(axis0), int(axis1)),
                       [x], {})


def rot90(x, k=1, axes=(0, 1), name=None):
    return eager_apply("rot90", lambda a: jnp.rot90(a, k, tuple(axes)), [x], {})


def flip(x, axis, name=None):
    ax = _ints(axis)
    ax = tuple(ax) if isinstance(ax, list) else (ax,)
    return eager_apply("flip", lambda a: jnp.flip(a, ax), [x], {})


def roll(x, shifts, axis=None, name=None):
    return eager_apply(
        "roll",
        lambda a: jnp.roll(a, _ints(shifts),
                           None if axis is None else _ints(axis)), [x], {})


for _n, _f in (("transpose", transpose), ("moveaxis", moveaxis),
               ("swapaxes", swapaxes), ("rot90", rot90), ("flip", flip),
               ("roll", roll)):
    _export(_n, _f, methods=[_n])


# ------------------------------------------------------- concat / split
def _concat_raw(*arrs, ax=0):
    return jnp.concatenate(arrs, ax)


def concat(x: Sequence[Tensor], axis=0, name=None):
    tensors = [_as_tensor(t) for t in x]
    ax = int(axis.item() if isinstance(axis, Tensor) else axis)
    return eager_apply("concat", _concat_raw, tensors, {"ax": ax})


def _stack_raw(*arrs, ax=0):
    return jnp.stack(arrs, ax)


def stack(x: Sequence[Tensor], axis=0, name=None):
    tensors = [_as_tensor(t) for t in x]
    return eager_apply("stack", _stack_raw, tensors, {"ax": int(axis)})


def split(x, num_or_sections, axis=0, name=None):
    x = _as_tensor(x)
    ax = int(axis.item() if isinstance(axis, Tensor) else axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {ax} (size {dim}) is not divisible by "
                f"num_or_sections={num_or_sections}")
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = _ints(num_or_sections)
        neg = [i for i, s in enumerate(sections) if s < 0]
        if neg:
            known = sum(s for s in sections if s >= 0)
            sections[neg[0]] = dim - known
    offsets = tuple(int(o) for o in np.cumsum([0] + sections))

    outs = eager_apply("split", _split_raw, [x],
                       {"offsets": offsets, "ax": ax},
                       n_outputs=len(sections))
    return list(outs)


def _split_raw(a, offsets=(), ax=0):
    return tuple(jax.lax.slice_in_dim(a, offsets[i], offsets[i + 1], axis=ax)
                 for i in range(len(offsets) - 1))


def chunk(x, chunks, axis=0, name=None):
    # paddle.chunk allows a smaller trailing chunk on non-divisible dims
    x = _as_tensor(x)
    ax = int(axis) % x.ndim
    dim = x.shape[ax]
    n = int(chunks)
    if dim % n == 0:
        return split(x, n, ax)
    per = -(-dim // n)  # ceil
    sections = [per] * (dim // per) + ([dim - per * (dim // per)]
                                       if dim % per else [])
    return split(x, sections, ax)


def unstack(x, axis=0, num=None, name=None):
    x = _as_tensor(x)
    ax = int(axis) % x.ndim
    n = num or x.shape[ax]

    def raw(a):
        return tuple(jnp.squeeze(s, ax)
                     for s in jnp.split(a, n, axis=ax))

    return list(eager_apply("unstack", raw, [x], {}, n_outputs=n))


def unbind(input, axis=0):
    return unstack(input, axis)


def tile(x, repeat_times, name=None):
    return eager_apply("tile", lambda a: jnp.tile(a, tuple(_ints(repeat_times))),
                       [x], {})


def expand(x, shape, name=None):
    x = _as_tensor(x)
    target = _ints(shape)
    cur = x.shape
    full = []
    for i, s in enumerate(target):
        if s in (-1, 0) and len(target) - i <= len(cur):
            full.append(cur[len(cur) - (len(target) - i)])
        else:
            full.append(s)
    return eager_apply("expand",
                       lambda a: jnp.broadcast_to(a, tuple(full)), [x], {})


def expand_as(x, y, name=None):
    return eager_apply("expand_as",
                       lambda a: jnp.broadcast_to(a, tuple(y.shape)), [x], {})


def broadcast_to(x, shape, name=None):
    return eager_apply("broadcast_to",
                       lambda a: jnp.broadcast_to(a, tuple(_ints(shape))),
                       [x], {})


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [broadcast_to(t, out_shape) for t in inputs]


for _n in ("concat", "stack", "split", "chunk", "unstack", "unbind", "tile",
           "expand", "expand_as", "broadcast_to", "broadcast_tensors"):
    _export(_n, globals()[_n],
            methods=[_n] if _n in ("split", "chunk", "tile", "expand",
                                   "expand_as", "broadcast_to", "unbind") else ())


# ------------------------------------------------------- gather/scatter
def _gather_raw(a, ind, ax=0):
    if ind.ndim == 2 and ind.shape[1] == 1:
        ind = ind.reshape(-1)
    return jnp.take(a, ind, axis=ax)


def gather(x, index, axis=0, name=None):
    ax = int(axis.item() if isinstance(axis, Tensor) else axis)
    # index as a (non-diff, integer) tensor input keeps the raw fn a
    # stable module-level object — admissible to the dispatch caches
    return eager_apply("gather", _gather_raw,
                       [_as_tensor(x), _as_tensor(index)], {"ax": ax})


def gather_nd(x, index, name=None):
    idx = _as_tensor(index)._data

    def raw(a):
        ind = tuple(jnp.moveaxis(idx, -1, 0))
        return a[ind]

    return eager_apply("gather_nd", raw, [x], {})


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = _as_tensor(indices)._data

    def raw(a):
        return jnp.take_along_axis(a, idx, axis=int(axis))

    return eager_apply("take_along_axis", raw, [arr], {})


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    idx = _as_tensor(indices)._data
    vals = _as_tensor(values)

    def raw(a, v):
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        dims = list(range(a.ndim))
        ax = int(axis) % a.ndim
        index_arrays = []
        for d in dims:
            if d == ax:
                index_arrays.append(idx)
            else:
                shape = [1] * a.ndim
                shape[d] = a.shape[d] if d >= idx.ndim or idx.shape[d] != 1 else 1
                r = jnp.arange(idx.shape[d] if d < idx.ndim else a.shape[d])
                sh = [1] * idx.ndim
                sh[d] = -1
                index_arrays.append(r.reshape(sh))
        at = a.at[tuple(jnp.broadcast_arrays(*index_arrays))]
        if reduce == "assign":
            return at.set(v)
        if reduce in ("add", "sum"):
            return at.add(v)
        if reduce in ("mul", "multiply"):
            return at.multiply(v)
        if reduce == "amax":
            return at.max(v)
        if reduce == "amin":
            return at.min(v)
        raise ValueError(f"unknown reduce {reduce}")

    return eager_apply("put_along_axis", raw, [arr, vals], {})


def scatter(x, index, updates, overwrite=True, name=None):
    idx = _as_tensor(index)._data.reshape(-1)

    def raw(a, u):
        if overwrite:
            return a.at[idx].set(u.astype(a.dtype))
        # paddle !overwrite: zero the rows then accumulate
        zeroed = a.at[idx].set(jnp.zeros_like(u, a.dtype))
        return zeroed.at[idx].add(u.astype(a.dtype))

    return eager_apply("scatter", raw, [x, _as_tensor(updates)], {})


def scatter_nd_add(x, index, updates, name=None):
    idx = _as_tensor(index)._data

    def raw(a, u):
        ind = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[ind].add(u.astype(a.dtype))

    return eager_apply("scatter_nd_add", raw, [x, _as_tensor(updates)], {})


def scatter_nd(index, updates, shape, name=None):
    u = _as_tensor(updates)
    zeros = Tensor(jnp.zeros(tuple(_ints(shape)), u._data.dtype))
    return scatter_nd_add(zeros, index, u)


def _index_select_raw(a, ind, ax=0):
    return jnp.take(a, ind.reshape(-1), axis=ax)


def index_select(x, index, axis=0, name=None):
    return eager_apply("index_select", _index_select_raw,
                       [_as_tensor(x), _as_tensor(index)],
                       {"ax": int(axis)})


def index_sample(x, index):
    idx = _as_tensor(index)._data

    def raw(a):
        return jnp.take_along_axis(a, idx, axis=1)

    return eager_apply("index_sample", raw, [x], {})


def index_add(x, index, axis, value, name=None):
    idx = _as_tensor(index)._data.reshape(-1)

    def raw(a, v):
        ax = int(axis) % a.ndim
        moved = jnp.moveaxis(a, ax, 0)
        vm = jnp.moveaxis(v.astype(a.dtype), ax, 0)
        out = moved.at[idx].add(vm)
        return jnp.moveaxis(out, 0, ax)

    return eager_apply("index_add", raw, [x, _as_tensor(value)], {})


def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(_as_tensor(i)._data for i in indices)

    def raw(a, v):
        at = a.at[idx]
        return at.add(v.astype(a.dtype)) if accumulate else at.set(v.astype(a.dtype))

    return eager_apply("index_put", raw, [x, _as_tensor(value)], {})


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = repeats._data

    def raw(a):
        return jnp.repeat(a, repeats, axis=None if axis is None else int(axis))

    return eager_apply("repeat_interleave", raw, [x], {})


for _n in ("gather", "gather_nd", "take_along_axis", "put_along_axis",
           "scatter", "scatter_nd_add", "scatter_nd", "index_select",
           "index_sample", "index_add", "index_put", "repeat_interleave"):
    _export(_n, globals()[_n], methods=[_n])


# ---------------------------------------------------------------- pad
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _as_tensor(x)
    p = _ints(pad)
    nd = x.ndim
    if len(p) == 2 * nd:
        # paddle flat form: [d0_left, d0_right, d1_left, ...] per *all* dims
        width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
    else:
        # NCHW/NCL/NCDHW spatial-only form, reversed pairs like torch
        n_spatial = len(p) // 2
        width = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial_dims = list(range(2, 2 + n_spatial))
        else:
            spatial_dims = list(range(1, 1 + n_spatial))
        for i, d in enumerate(reversed(spatial_dims)):
            width[d] = (p[2 * i], p[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def raw(a):
        if jmode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return eager_apply("pad", raw, [x], {})


_export("pad", pad)


# ------------------------------------------------------ sort / search
def _topk_raw(a, kk=1, ax=-1, largest=True):
    src = jnp.moveaxis(a, ax, -1)
    if largest:
        v, i = jax.lax.top_k(src, kk)
    else:
        v, i = jax.lax.top_k(-src, kk)
        v = -v
    return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = _as_tensor(x)
    kk = int(k.item() if isinstance(k, Tensor) else k)
    ax = int(axis)

    vals, idx = eager_apply("topk", _topk_raw, [x],
                            {"kk": kk, "ax": ax, "largest": bool(largest)},
                            n_outputs=2)
    return vals, Tensor(idx._data.astype(jnp.int64))


def _sort_raw(a, ax=-1, descending=False):
    s = jnp.sort(a, axis=ax, stable=True)
    return jnp.flip(s, ax) if descending else s


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return eager_apply("sort", _sort_raw, [x],
                       {"ax": int(axis), "descending": bool(descending)})


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    x = _as_tensor(x)
    i = jnp.argsort(x._data, axis=int(axis), stable=True)
    if descending:
        i = jnp.flip(i, int(axis))
    return Tensor(i.astype(jnp.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    seq = _as_tensor(sorted_sequence)._data
    v = _as_tensor(values)._data
    side = "right" if right else "left"
    if seq.ndim == 1:
        out = jnp.searchsorted(seq, v, side=side)
    else:
        flat_seq = seq.reshape(-1, seq.shape[-1])
        flat_v = v.reshape(-1, v.shape[-1])
        out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
            flat_seq, flat_v).reshape(v.shape)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


for _n in ("topk", "sort", "argsort", "searchsorted", "bucketize"):
    _export(_n, globals()[_n], methods=[_n],
            differentiable=_n in ("topk", "sort"))


# ---------------------------------------- dynamic-shape (eager-only) ops
def nonzero(x, as_tuple=False):
    a = np.asarray(_as_tensor(x)._data)
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None].astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def masked_select(x, mask, name=None):
    a = np.asarray(_as_tensor(x)._data)
    m = np.asarray(_as_tensor(mask)._data).astype(bool)
    return Tensor(jnp.asarray(a[np.broadcast_to(m, a.shape)]))


def masked_fill(x, mask, value, name=None):
    m = _as_tensor(mask)._data
    v = value.item() if isinstance(value, Tensor) else value
    return eager_apply("masked_fill",
                       lambda a: jnp.where(m, jnp.asarray(v, a.dtype), a),
                       [x], {})


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    a = np.asarray(_as_tensor(x)._data)
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts,
                    axis=None if axis is None else int(axis))
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(res))
    res = list(res if isinstance(res, tuple) else (res,))
    outs = [Tensor(jnp.asarray(res[0]))]
    for r in res[1:]:
        outs.append(Tensor(jnp.asarray(r.astype(np.int64))))
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = np.asarray(_as_tensor(x)._data)
    if axis is None:
        a = a.reshape(-1)
        ax = 0
    else:
        ax = int(axis) % a.ndim
        a = np.moveaxis(a, ax, 0)
    if a.shape[0] == 0:
        keep = np.zeros((0,), bool)
    else:
        flat = a.reshape(a.shape[0], -1)
        keep = np.concatenate([[True], np.any(flat[1:] != flat[:-1], axis=1)])
    vals = a[keep]
    if axis is not None:
        vals = np.moveaxis(vals, 0, ax)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, a.shape[0]))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


for _n in ("nonzero", "masked_select", "masked_fill", "unique",
           "unique_consecutive"):
    _export(_n, globals()[_n], methods=[_n],
            differentiable=_n == "masked_fill")


# ------------------------------------------------------------- casting
def _cast_raw(a, d=None):
    return a.astype(d)


def cast(x, dtype):
    x = _as_tensor(x)
    d = to_jax_dtype(dtype)
    if jnp.issubdtype(d, jnp.inexact) and jnp.issubdtype(x._data.dtype, jnp.inexact):
        return eager_apply("cast", _cast_raw, [x], {"d": np.dtype(d)})
    return Tensor(x._data.astype(d))


def astype(x, dtype):
    return cast(x, dtype)


_export("cast", cast, methods=["cast", "astype"])


def slice(input, axes, starts, ends):
    axes, starts, ends = _ints(axes), _ints(starts), _ints(ends)

    def raw(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            dim = a.shape[ax]
            s2 = max(s + dim, 0) if s < 0 else min(s, dim)
            e2 = max(e + dim, 0) if e < 0 else min(e, dim)
            idx[ax] = builtins.slice(s2, e2)
        return a[tuple(idx)]

    return eager_apply("slice", raw, [input], {})


_export("slice", slice)


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes, starts, ends, strides = (_ints(axes), _ints(starts), _ints(ends),
                                   _ints(strides))

    def raw(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]

    return eager_apply("strided_slice", raw, [x], {})


_export("strided_slice", strided_slice)


def crop(x, shape=None, offsets=None, name=None):
    x = _as_tensor(x)
    shape = _ints(shape) if shape is not None else x.shape
    offsets = _ints(offsets) if offsets is not None else [0] * x.ndim
    shape = [x.shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]

    def raw(a):
        return jax.lax.dynamic_slice(a, offsets, shape)

    return eager_apply("crop", raw, [x], {})


_export("crop", crop)


def as_complex(x, name=None):
    return eager_apply("as_complex",
                       lambda a: jax.lax.complex(a[..., 0], a[..., 1]), [x], {})


def as_real(x, name=None):
    return eager_apply(
        "as_real",
        lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), [x], {})


def real(x, name=None):
    return eager_apply("real", lambda a: jnp.real(a), [x], {})


def imag(x, name=None):
    return eager_apply("imag", lambda a: jnp.imag(a), [x], {})


for _n in ("as_complex", "as_real", "real", "imag"):
    _export(_n, globals()[_n], methods=[_n])
