"""Elementwise math + reduction ops.

Reference surface: python/paddle/tensor/math.py and python/paddle/tensor/
stat.py; kernels under paddle/phi/kernels (cpu/gpu elementwise + reduce).
Here each op is a thin functional jnp lambda dispatched through
``eager_apply`` — XLA fuses elementwise chains into matmul/reduce
neighbors on TPU, so there is no need for hand-fused variants on the
forward path.
"""
from __future__ import annotations

import math as _math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype, get_default_dtype, to_jax_dtype
from ..core.tensor import Tensor
from .dispatch import eager_apply
from .registry import register_op

__all__: list = []


def _export(name, fn, methods=(), differentiable=True):
    globals()[name] = fn
    __all__.append(name)
    register_op(name, fn, methods=methods, differentiable=differentiable,
                tags=("math",))
    return fn


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


# ---------------------------------------------------------------- unary
_UNARY = {
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p, "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt, "abs": jnp.abs, "sin": jnp.sin, "cos": jnp.cos,
    "tan": jnp.tan, "asin": jnp.arcsin, "acos": jnp.arccos,
    "atan": jnp.arctan, "sinh": jnp.sinh, "cosh": jnp.cosh,
    "tanh": jnp.tanh, "asinh": jnp.arcsinh, "acosh": jnp.arccosh,
    "atanh": jnp.arctanh, "sigmoid": jax.nn.sigmoid, "floor": jnp.floor,
    "ceil": jnp.ceil, "round": jnp.round, "trunc": jnp.trunc,
    "sign": jnp.sign, "reciprocal": lambda a: 1.0 / a,
    "square": jnp.square, "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv, "digamma": jax.scipy.special.digamma,
    "lgamma": jax.scipy.special.gammaln, "neg": jnp.negative,
    "conj": jnp.conj, "angle": jnp.angle, "frac": lambda a: a - jnp.trunc(a),
    "i0": jax.scipy.special.i0, "i1": jax.scipy.special.i1,
}


def _make_unary(name, jfn):
    def op(x, name=None, _jfn=jfn, _opname=name):
        return eager_apply(_opname, _jfn, [_as_tensor(x)], {})

    op.__name__ = name
    return op


for _n, _f in _UNARY.items():
    _op = _make_unary(_n, _f)
    _methods = [_n]
    _export(_n, _op, methods=_methods)

Tensor._attach_method("__neg__", globals()["neg"])
Tensor._attach_method("__abs__", globals()["abs"])


# --------------------------------------------------------------- binary
def _make_binary(name, jfn, int_to_float=False):
    # stable per-op raws (created once at registration, not per call) so
    # the scalar-operand and int-promoting paths stay cache-admissible
    def promoted(a, b, d=None, _jfn=jfn):
        return _jfn(a.astype(d), b.astype(d))

    def right_scalar(a, y=None, _jfn=jfn):
        return _jfn(a, y)

    def left_scalar(b, x=None, _jfn=jfn):
        return _jfn(x, b)

    def op(x, y, name=None, _jfn=jfn, _opname=name):
        xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
        if xt and yt:
            if int_to_float and (jnp.issubdtype(x._data.dtype, jnp.integer)
                                 and jnp.issubdtype(y._data.dtype, jnp.integer)):
                return eager_apply(_opname, promoted, [x, y],
                                   {"d": get_default_dtype().np_dtype})
            return eager_apply(_opname, _jfn, [x, y], {})
        if xt:
            return eager_apply(_opname, right_scalar, [x], {"y": y})
        if yt:
            return eager_apply(_opname, left_scalar, [y], {"x": x})
        return Tensor(jnp.asarray(_jfn(x, y)))

    op.__name__ = name
    return op


_BINARY = {
    "add": (jnp.add, ["add", "__add__", "__radd__"]),
    "subtract": (jnp.subtract, ["subtract", "__sub__"]),
    "multiply": (jnp.multiply, ["multiply", "__mul__", "__rmul__"]),
    "divide": (jnp.true_divide, ["divide", "__truediv__"]),
    "floor_divide": (jnp.floor_divide, ["floor_divide", "__floordiv__"]),
    "mod": (jnp.mod, ["mod", "__mod__"]),
    "remainder": (jnp.remainder, ["remainder"]),
    "pow": (jnp.power, ["pow", "__pow__"]),
    "maximum": (jnp.maximum, ["maximum"]),
    "minimum": (jnp.minimum, ["minimum"]),
    "fmax": (jnp.fmax, ["fmax"]),
    "fmin": (jnp.fmin, ["fmin"]),
    "atan2": (jnp.arctan2, ["atan2"]),
    "logaddexp": (jnp.logaddexp, ["logaddexp"]),
    "hypot": (jnp.hypot, ["hypot"]),
    "copysign": (jnp.copysign, ["copysign"]),
    "heaviside": (jnp.heaviside, ["heaviside"]),
    "gcd": (jnp.gcd, ["gcd"]),
    "lcm": (jnp.lcm, ["lcm"]),
}

for _n, (_f, _methods) in _BINARY.items():
    _op = _make_binary(_n, _f, int_to_float=(_n == "divide"))
    _export(_n, _op, methods=_methods)


def _rsub(self, other):
    return globals()["subtract"](other, self)


def _rdiv(self, other):
    return globals()["divide"](other, self)


def _rpow(self, other):
    return globals()["pow"](other, self)


Tensor._attach_method("__rsub__", _rsub)
Tensor._attach_method("__rtruediv__", _rdiv)
Tensor._attach_method("__rpow__", _rpow)


# ---------------------------------------------------- scalar-attr ops
def _scale_raw(a, s=1.0, bias=0.0, bias_after_scale=True):
    out = a * s + bias if bias_after_scale else (a + bias) * s
    return out.astype(a.dtype)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale
    out = eager_apply("scale", _scale_raw, [_as_tensor(x)],
                      {"s": s, "bias": bias,
                       "bias_after_scale": bool(bias_after_scale)})
    if act is not None:
        out = globals()[act](out)
    return out


_export("scale", scale, methods=["scale"])


def _clip_raw(a, mn=None, mx=None):
    return jnp.clip(a, mn, mx)


def clip(x, min=None, max=None, name=None):
    tensors = [_as_tensor(x)]
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return eager_apply("clip", _clip_raw, tensors, {"mn": mn, "mx": mx})


_export("clip", clip, methods=["clip"])


def _lerp_raw(a, b, w):
    return a + w * (b - a)


def _lerp_scalar_raw(a, b, weight=0.0):
    return a + weight * (b - a)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return eager_apply("lerp", _lerp_raw, [x, y, weight], {})
    return eager_apply("lerp", _lerp_scalar_raw, [x, y],
                       {"weight": weight})


_export("lerp", lerp, methods=["lerp"])


def _stanh_raw(a, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * a)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return eager_apply("stanh", _stanh_raw, [_as_tensor(x)],
                       {"scale_a": scale_a, "scale_b": scale_b})


_export("stanh", stanh)


def _addmm_raw(i, a, b, beta=1.0, alpha=1.0):
    return beta * i + alpha * (a @ b)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return eager_apply("addmm", _addmm_raw, [input, x, y],
                       {"beta": beta, "alpha": alpha})


_export("addmm", addmm)


def multiplex(inputs, index, name=None):
    stacked = jnp.stack([t._data for t in inputs], axis=0)

    def raw(idx, *arrs):
        st = jnp.stack(arrs, axis=0)
        rows = jnp.arange(st.shape[1])
        return st[idx.reshape(-1), rows]

    return eager_apply("multiplex", lambda *arrs: raw(index._data, *arrs),
                       list(inputs), {})


_export("multiplex", multiplex)


# ------------------------------------------------------------ reductions
def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _make_reduce(name, jfn, int_promote=False):
    # one stable raw per reduce op (axis/keepdim/dtype as static kwargs)
    # so reductions are admissible to the signature-keyed dispatch caches
    def raw(a, ax=None, keepdim=False, dtype=None, _jfn=jfn,
            _promote=int_promote):
        if dtype is not None:
            a = a.astype(to_jax_dtype(dtype))
        elif _promote and jnp.issubdtype(a.dtype, jnp.integer):
            a = a.astype(jnp.int64)
        return _jfn(a, axis=ax, keepdims=keepdim)

    def op(x, axis=None, keepdim=False, name=None, dtype=None,
           _opname=name, _raw=raw):
        x = _as_tensor(x)
        return eager_apply(_opname, _raw, [x],
                           {"ax": _axis_arg(axis), "keepdim": bool(keepdim),
                            "dtype": dtype})

    op.__name__ = name
    return op


_REDUCE = {
    "sum": (jnp.sum, True), "mean": (jnp.mean, False),
    "prod": (jnp.prod, True), "max": (jnp.max, False),
    "min": (jnp.min, False), "amax": (jnp.amax, False),
    "amin": (jnp.amin, False), "nansum": (jnp.nansum, True),
    "nanmean": (jnp.nanmean, False),
    "logsumexp": (jax.scipy.special.logsumexp, False),
    "all": (jnp.all, False), "any": (jnp.any, False),
}

for _n, (_f, _p) in _REDUCE.items():
    _op = _make_reduce(_n, _f, _p)
    _export(_n, _op, methods=[_n], differentiable=_n not in ("all", "any"))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.count_nonzero(_as_tensor(x)._data, axis=_axis_arg(axis),
                                    keepdims=keepdim).astype(jnp.int64))


_export("count_nonzero", count_nonzero, differentiable=False)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = _as_tensor(x)
    ax = None if axis is None else int(axis)
    out = jnp.argmax(x._data, axis=ax, keepdims=keepdim if ax is not None else False)
    return Tensor(out.astype(to_jax_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = _as_tensor(x)
    ax = None if axis is None else int(axis)
    out = jnp.argmin(x._data, axis=ax, keepdims=keepdim if ax is not None else False)
    return Tensor(out.astype(to_jax_dtype(dtype)))


_export("argmax", argmax, methods=["argmax"], differentiable=False)
_export("argmin", argmin, methods=["argmin"], differentiable=False)


def cumsum(x, axis=None, dtype=None, name=None):
    x = _as_tensor(x)

    def raw(a):
        if dtype is not None:
            a = a.astype(to_jax_dtype(dtype))
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=int(axis))

    return eager_apply("cumsum", raw, [x], {})


def cumprod(x, dim=None, dtype=None, name=None):
    x = _as_tensor(x)

    def raw(a):
        if dtype is not None:
            a = a.astype(to_jax_dtype(dtype))
        return jnp.cumprod(a, axis=int(dim) if dim is not None else None)

    return eager_apply("cumprod", raw, [x], {})


def _running_arg_scan(a, ax, cmp):
    """Running (value, first-index) scan along ax — associative combiner:
    keep the earlier index on ties."""
    idx_shape = [1] * a.ndim
    idx_shape[ax] = a.shape[ax]
    idx0 = jnp.broadcast_to(
        jnp.arange(a.shape[ax], dtype=jnp.int64).reshape(idx_shape), a.shape)

    def comb(lhs, rhs):
        lv, li = lhs
        rv, ri = rhs
        take_r = cmp(rv, lv)  # strict: ties keep the earlier (left) index
        return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

    return jax.lax.associative_scan(comb, (a, idx0), axis=ax)


def cummax(x, axis=None, dtype="int64", name=None):
    x = _as_tensor(x)
    ax = 0 if axis is None else int(axis)
    a = x._data if axis is not None else x._data.reshape(-1)
    _, idx = _running_arg_scan(a, ax % a.ndim, jnp.greater)
    out = eager_apply("cummax", lambda b: jax.lax.associative_scan(
        jnp.maximum, b if axis is not None else b.reshape(-1), axis=ax), [x], {})
    return out, Tensor(idx.astype(to_jax_dtype(dtype)))


def cummin(x, axis=None, dtype="int64", name=None):
    x = _as_tensor(x)
    ax = 0 if axis is None else int(axis)
    a = x._data if axis is not None else x._data.reshape(-1)
    _, idx = _running_arg_scan(a, ax % a.ndim, jnp.less)
    out = eager_apply("cummin", lambda b: jax.lax.associative_scan(
        jnp.minimum, b if axis is not None else b.reshape(-1), axis=ax), [x], {})
    return out, Tensor(idx.astype(to_jax_dtype(dtype)))


_export("cumsum", cumsum, methods=["cumsum"])
_export("cumprod", cumprod, methods=["cumprod"])
_export("cummax", cummax)
_export("cummin", cummin)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = _as_tensor(x)
    return eager_apply("median",
                       lambda a: jnp.median(a, axis=_axis_arg(axis),
                                            keepdims=keepdim), [x], {})


def quantile(x, q, axis=None, keepdim=False, name=None, interpolation="linear"):
    x = _as_tensor(x)
    return eager_apply(
        "quantile",
        lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis_arg(axis),
                               keepdims=keepdim, method=interpolation),
        [x], {})


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = _as_tensor(x)
    return eager_apply(
        "std",
        lambda a: jnp.std(a, axis=_axis_arg(axis), ddof=1 if unbiased else 0,
                          keepdims=keepdim), [x], {})


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = _as_tensor(x)
    return eager_apply(
        "var",
        lambda a: jnp.var(a, axis=_axis_arg(axis), ddof=1 if unbiased else 0,
                          keepdims=keepdim), [x], {})


_export("median", median, methods=["median"])
_export("quantile", quantile)
_export("std", std, methods=["std"])
_export("var", var, methods=["var"])


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = _as_tensor(x)
    ax = int(axis)

    def raw(a):
        s = jnp.sort(a, axis=ax)
        v = jnp.take(s, k - 1, axis=ax)
        return jnp.expand_dims(v, ax) if keepdim else v

    vals = eager_apply("kthvalue", raw, [x], {})
    idx = jnp.take(jnp.argsort(x._data, axis=ax), k - 1, axis=ax)
    if keepdim:
        idx = jnp.expand_dims(idx, ax)
    return vals, Tensor(idx.astype(jnp.int64))


_export("kthvalue", kthvalue)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return eager_apply("trace",
                       lambda a: jnp.trace(a, offset, int(axis1), int(axis2)),
                       [_as_tensor(x)], {})


_export("trace", trace, methods=["trace"])


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return eager_apply("nan_to_num",
                       lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                                neginf=neginf),
                       [_as_tensor(x)], {})


_export("nan_to_num", nan_to_num, methods=["nan_to_num"])


def log_softmax_raw(a, axis):
    return jax.nn.log_softmax(a, axis=axis)


def _increment_raw(a, value=1.0):
    return a + value


def increment(x, value=1.0, name=None):
    from .dispatch import inplace_apply

    return inplace_apply("increment", _increment_raw, [x],
                         {"value": value})


_export("increment", increment)
register_op("increment_", increment, inplace_of="increment",
            donates=(0,), tags=("math", "inplace"))


def outer(x, y, name=None):
    return eager_apply("outer",
                       lambda a, b: jnp.outer(a, b), [x, y], {})


def inner(x, y, name=None):
    return eager_apply("inner", lambda a, b: jnp.inner(a, b), [x, y], {})


_export("outer", outer, methods=["outer"])
_export("inner", inner, methods=["inner"])


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


_export("broadcast_shape", broadcast_shape, differentiable=False)
