"""Random ops.

Reference surface: python/paddle/tensor/random.py; the stateful-seed
semantics come from the framework Generator (see core/generator.py — the
stateful shell over jax functional keys, reference phi/core/generator.h).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import get_default_dtype, to_jax_dtype
from ..core.generator import default_generator, next_rng_key
from ..core.tensor import Tensor
from .registry import register_op

__all__ = [
    "uniform", "uniform_", "normal", "normal_", "standard_normal", "randn",
    "rand", "randint", "randint_like", "randperm", "bernoulli", "poisson",
    "multinomial", "exponential_", "rand_like", "randn_like", "gumbel_softmax",
]


def _dt(dtype):
    return get_default_dtype().np_dtype if dtype is None else to_jax_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item() if isinstance(s, Tensor) else s) for s in shape)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    key = (jax.random.key(seed) if seed else next_rng_key())
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=float(min), maxval=float(max)))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    x._rebind(jax.random.uniform(next_rng_key(),
                                 tuple(x._data.shape), x._data.dtype,
                                 minval=float(min), maxval=float(max)))
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        out_shape = np.broadcast_shapes(np.shape(m), np.shape(s))
        eps = jax.random.normal(next_rng_key(), out_shape,
                                get_default_dtype().np_dtype)
        return Tensor(m + s * eps)
    eps = jax.random.normal(next_rng_key(), _shape(shape),
                            get_default_dtype().np_dtype)
    return Tensor(mean + std * eps)


def normal_(x, mean=0.0, std=1.0, name=None) -> Tensor:
    eps = jax.random.normal(next_rng_key(),
                            tuple(x._data.shape), x._data.dtype)
    x._rebind(mean + std * eps)
    return x


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.normal(next_rng_key(),
                                    _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None) -> Tensor:
    return standard_normal(shape, dtype)


def rand(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.uniform(next_rng_key(),
                                     _shape(shape), _dt(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_rng_key(),
                                     _shape(shape), int(low), int(high),
                                     to_jax_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def randperm(n, dtype="int64", name=None) -> Tensor:
    return Tensor(jax.random.permutation(next_rng_key(),
                                         int(n)).astype(to_jax_dtype(dtype)))


def bernoulli(x, p=None, name=None) -> Tensor:
    probs = x._data if p is None else p
    return Tensor(
        jax.random.bernoulli(next_rng_key(),
                             probs, tuple(np.shape(probs))).astype(
                                 x._data.dtype if p is None else jnp.float32))


def poisson(x, name=None) -> Tensor:
    return Tensor(jax.random.poisson(next_rng_key(),
                                     x._data).astype(x._data.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    key = next_rng_key()
    probs = x._data
    if probs.ndim == 1:
        out = jax.random.choice(key, probs.shape[0], (int(num_samples),),
                                replace=replacement, p=probs / probs.sum())
        return Tensor(out.astype(jnp.int64))
    keys = jax.random.split(key, probs.shape[0])
    rows = [jax.random.choice(k, probs.shape[1], (int(num_samples),),
                              replace=replacement, p=r / r.sum())
            for k, r in zip(keys, probs)]
    return Tensor(jnp.stack(rows).astype(jnp.int64))


def exponential_(x, lam=1.0, name=None) -> Tensor:
    e = jax.random.exponential(next_rng_key(),
                               tuple(x._data.shape), x._data.dtype)
    x._rebind(e / lam)
    return x


def rand_like(x, dtype=None, name=None) -> Tensor:
    return rand(tuple(x.shape), dtype or x.dtype)


def randn_like(x, dtype=None, name=None) -> Tensor:
    return randn(tuple(x.shape), dtype or x.dtype)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from .dispatch import eager_apply

    g = -jnp.log(-jnp.log(
        jax.random.uniform(next_rng_key(),
                           tuple(x.shape), x._data.dtype) + 1e-20) + 1e-20)

    def raw(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            ax = axis % y.ndim
            one_hot = jnp.moveaxis(
                jax.nn.one_hot(jnp.argmax(y, axis=ax), y.shape[ax],
                               dtype=y.dtype), -1, ax)
            # straight-through estimator
            return one_hot + y - jax.lax.stop_gradient(y)
        return y

    return eager_apply("gumbel_softmax", raw, [x], {})


for _n in __all__:
    register_op(_n, globals()[_n], tags=("random",),
                differentiable=_n == "gumbel_softmax")
Tensor._attach_method("uniform_", uniform_)
Tensor._attach_method("normal_", normal_)
Tensor._attach_method("exponential_", exponential_)
Tensor._attach_method("bernoulli", bernoulli)
Tensor._attach_method("multinomial", multinomial)
