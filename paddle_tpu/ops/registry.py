"""Single-source op registry.

TPU-native equivalent of the reference's YAML op registry
(reference: paddle/phi/api/yaml/ops.yaml — the single source of truth from
which Paddle generates C++ API, autograd functions, Python bindings and
SPMD variants; generators under paddle/phi/api/yaml/generator/).

Here the registry is the single source from which we derive: the module-
level functional API (``paddle_tpu.matmul``), Tensor methods
(``t.matmul``), the ``_C_ops`` raw-dispatch namespace, and the op
inventory that tests validate against. Gradients and sharding rules need
no per-op tables: JAX vjp and XLA GSPMD propagation supply them from the
same functional definition (rule overrides registered per-op when XLA's
default is suboptimal).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

__all__ = ["OpDef", "register_op", "get_op", "all_ops",
           "op_call_counts", "inplace_ops"]


class OpDef:
    __slots__ = ("name", "fn", "methods", "differentiable", "inplace_of",
                 "tags", "donates")

    def __init__(self, name: str, fn: Callable, methods: Sequence[str] = (),
                 differentiable: bool = True, inplace_of: Optional[str] = None,
                 tags: Sequence[str] = (), donates: Sequence[int] = ()):
        self.name = name
        self.fn = fn
        self.methods = tuple(methods)
        self.differentiable = differentiable
        self.inplace_of = inplace_of
        self.tags = tuple(tags)
        #: positional tensor slots whose buffers the op may DONATE to its
        #: compiled no-grad executable (the in-place family: the slot is
        #: rebound to the output, so its old buffer can die in place —
        #: ops/dispatch.py inplace_apply)
        self.donates = tuple(donates)


_REGISTRY: Dict[str, OpDef] = {}


def register_op(name: str, fn: Callable, methods: Sequence[str] = (),
                differentiable: bool = True, inplace_of: Optional[str] = None,
                tags: Sequence[str] = (), donates: Sequence[int] = ()) -> Callable:
    """Register ``fn`` as op ``name``; attach Tensor methods listed in
    ``methods``. Returns fn unchanged so it can be used at module level."""
    from ..core.tensor import Tensor

    _REGISTRY[name] = OpDef(name, fn, methods, differentiable, inplace_of,
                            tags, donates)
    for m in methods:
        Tensor._attach_method(m, fn)
    return fn


def get_op(name: str) -> OpDef:
    return _REGISTRY[name]


def all_ops() -> Dict[str, OpDef]:
    return dict(_REGISTRY)


def inplace_ops() -> Dict[str, OpDef]:
    """The registered in-place family (``inplace_of`` set): ops that
    rebind their target and therefore participate in buffer donation on
    the compiled-forward fast path."""
    return {n: d for n, d in _REGISTRY.items() if d.inplace_of}


def op_call_counts(include_unused: bool = False) -> Dict[str, int]:
    """Registry inventory joined with the runtime telemetry: how many
    times each REGISTERED op was eager-dispatched this process (the
    ``op.<name>`` counters profiler.stats accumulates in eager_apply).
    With ``include_unused`` the never-dispatched ops appear as 0 —
    the coverage view the reference derives from its op-stat tables."""
    from ..profiler import stats

    counts = stats.snapshot()["counters"]
    out = {}
    for name in _REGISTRY:
        n = counts.get(f"op.{name}", 0)
        if n or include_unused:
            out[name] = n
    return out
