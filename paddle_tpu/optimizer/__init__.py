"""paddle_tpu.optimizer — mirrors python/paddle/optimizer."""
from . import lr  # noqa: F401
from .adam import Adam, AdamW, Lamb  # noqa: F401
from .optimizer import (  # noqa: F401
    Adadelta, Adagrad, Momentum, Optimizer, RMSProp, SGD,
)

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp",
    "Adam", "AdamW", "Lamb", "lr",
]
