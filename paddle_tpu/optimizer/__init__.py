"""paddle_tpu.optimizer — mirrors python/paddle/optimizer."""
from . import lr  # noqa: F401
from .adam import Adam, Adamax, AdamW, Lamb  # noqa: F401
from .lars import Lars  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from .optimizer import (  # noqa: F401
    Adadelta, Adagrad, Momentum, Optimizer, RMSProp, Rprop, SGD,
)

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp",
    "Rprop", "Adam", "AdamW", "Adamax", "Lamb", "LBFGS", "Lars", "lr",
]
