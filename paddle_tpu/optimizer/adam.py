"""Adam-family optimizers (reference: python/paddle/optimizer/adam.py,
adamw.py, lamb.py — fused multi_tensor adam kernels
phi/kernels/gpu/adam_kernel.cu). All run through the base's single
compiled pytree update."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..regularizer import L2Decay
from .optimizer import Optimizer

__all__ = ["Adam", "AdamW", "Lamb", "Adamax"]


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None,
                 moment_dtype=None, stochastic_rounding=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision, stochastic_rounding)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        if moment_dtype in ("bfloat16", "bf16"):
            self._moment_dtype = jnp.bfloat16
        elif moment_dtype not in (None, "float32", "fp32"):
            raise ValueError(f"unsupported moment_dtype {moment_dtype!r}")

    def _lowprec_state_keys(self):
        if self._moment_dtype is None:
            return frozenset()
        return frozenset({"moment1", "moment2", "moment2_max"})

    def _init_state(self, p):
        md = self._moment_dtype or p._data.dtype
        st = {
            "moment1": jnp.zeros(p._data.shape, md),
            "moment2": jnp.zeros(p._data.shape, md),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }
        if self._amsgrad:
            st["moment2_max"] = jnp.zeros(p._data.shape, md)
        return st

    def _rule(self, p, g, state, hyper):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        cd = p.dtype  # compute dtype (fp32 master / upcast param)
        m1 = b1 * state["moment1"].astype(cd) + (1 - b1) * g
        m2 = b2 * state["moment2"].astype(cd) + (1 - b2) * g * g
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m1_hat = m1 / (1 - b1p).astype(cd)
        if self._amsgrad:
            m2_max = jnp.maximum(state["moment2_max"].astype(cd), m2)
            m2_hat = m2_max / (1 - b2p).astype(cd)
        else:
            m2_hat = m2 / (1 - b2p).astype(cd)
        new_p = p - hyper["lr"] * m1_hat / (jnp.sqrt(m2_hat) + eps)
        st = {"moment1": self._moment_store(m1),
              "moment2": self._moment_store(m2),
              "beta1_pow": b1p, "beta2_pow": b2p}
        if self._amsgrad:
            st["moment2_max"] = self._moment_store(m2_max)
        return new_p, st


class Adamax(Optimizer):
    """Adamax — Adam with an infinity-norm second moment (reference:
    python/paddle/optimizer/adamax.py:27, kernel
    phi/kernels/impl/adamax_kernel_impl.h): m = b1*m + (1-b1)*g,
    u = max(|g|, b2*u + eps), p -= lr/(1-b1^t) * m/u. No bias
    correction on u (the max recursion is already scale-stable); the
    epsilon rides inside the max (keeps u > 0), reference semantics."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, p):
        return {
            "moment": jnp.zeros_like(p._data),
            "inf_norm": jnp.zeros_like(p._data),
            "beta1_pow": jnp.ones((), jnp.float32),
        }

    def _rule(self, p, g, state, hyper):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(jnp.abs(g), b2 * state["inf_norm"] + eps)
        b1p = state["beta1_pow"] * b1
        lr_t = hyper["lr"] / (1 - b1p).astype(p.dtype)
        new_p = p - lr_t * m / u
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class AdamW(Adam):
    """Decoupled weight decay (reference: optimizer/adamw.py — wd applied to
    the param, not folded into the grad)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None, moment_dtype=None, stochastic_rounding=False):
        coeff = weight_decay if isinstance(weight_decay, float) else (
            weight_decay.coeff if isinstance(weight_decay, L2Decay) else 0.01)
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name,
                         moment_dtype=moment_dtype,
                         stochastic_rounding=stochastic_rounding)
        self._coeff = float(coeff)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        self._no_decay_ids = set()

    def _decoupled_wd(self):
        return True

    def _apply_optimize(self, params_grads):
        if self._apply_decay_param_fun is not None:
            self._no_decay_ids = {
                id(p) for p, _ in params_grads
                if not self._apply_decay_param_fun(p.name)}
        super()._apply_optimize(params_grads)

    def _hyper(self):
        h = super()._hyper()
        h["coeff"] = self._coeff
        return h

    def _per_param_hyper(self, p):
        h = super()._per_param_hyper(p)
        h["wd_mask"] = 0.0 if id(p) in self._no_decay_ids else 1.0
        if self._lr_ratio is not None:
            h["lr_mult"] = h["lr_mult"] * float(self._lr_ratio(p))
        return h

    def _rule(self, p, g, state, hyper):
        # decoupled decay first: p *= (1 - lr*coeff)
        p = p * (1.0 - hyper["lr"] * hyper["coeff"] * hyper["wd_mask"])
        return super()._rule(p, g, state, hyper)


class Lamb(Optimizer):
    """LAMB (reference: optimizer/lamb.py) — layerwise trust-ratio Adam."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._data),
            "moment2": jnp.zeros_like(p._data),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _rule(self, p, g, state, hyper):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m1 = b1 * state["moment1"] + (1 - b1) * g
        m2 = b2 * state["moment2"] + (1 - b2) * g * g
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        r = m1_hat / (jnp.sqrt(m2_hat) + eps) + self._wd * p
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        new_p = p - hyper["lr"] * trust * r
        return new_p, {"moment1": m1, "moment2": m2, "beta1_pow": b1p,
                       "beta2_pow": b2p}
