"""LARS — layer-wise adaptive rate scaling for large-batch SGD.

TPU-native counterpart of the reference's LARS stack (reference:
python/paddle/distributed/fleet/meta_optimizers/lars_optimizer.py wraps
fluid's LarsMomentumOptimizer; kernel
phi/kernels/gpu/lars_momentum_kernel.cu). Here it is a plain pytree
optimizer — the trust-ratio rule runs inside the same single compiled
multi-tensor update every other optimizer uses, so it composes with
TrainStep/data-parallel meshes with no meta-optimizer plumbing.
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["Lars"]


class Lars(Optimizer):
    """Per-layer trust ratio:

        local_lr = lr * lars_coeff * ||p|| /
                   (||g|| + lars_weight_decay * ||p|| + epsilon)
        v        = momentum * v + local_lr * (g + lars_weight_decay * p)
        p       -= v

    ``exclude_from_weight_decay`` entries (name substrings, reference
    semantics) skip both the decay term and the trust-ratio scaling —
    those layers fall back to plain momentum SGD.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, epsilon=0.0,
                 exclude_from_weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._lars_coeff = float(lars_coeff)
        self._lars_wd = float(lars_weight_decay)
        self._epsilon = float(epsilon)
        self._exclude = list(exclude_from_weight_decay or [])

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._data)}

    def _per_param_hyper(self, p):
        h = super()._per_param_hyper(p)
        excluded = any(s in (p.name or "") for s in self._exclude)
        h["lars_mask"] = 0.0 if excluded else 1.0
        return h

    def _rule(self, p, g, state, hyper):
        f32 = jnp.float32
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(f32))))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(f32))))
        mask = hyper["lars_mask"]
        wd = self._lars_wd * mask
        trust = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm /
            (g_norm + wd * p_norm + self._epsilon),
            1.0)
        local_lr = hyper["lr"] * jnp.where(mask > 0, trust, 1.0)
        v = self._momentum * state["velocity"] + \
            local_lr.astype(p.dtype) * (g + wd * p)
        return p - v, {"velocity": v}
