"""L-BFGS with optional strong-Wolfe line search.

TPU-native counterpart of the reference's full line-search optimizer
(reference: python/paddle/optimizer/lbfgs.py:307 — ``LBFGS.step(closure)``
re-evaluates the loss through a user closure; two-loop recursion over an
(s, y) history approximates the inverse Hessian). Quasi-Newton iteration
is inherently host-sequential (each inner iteration's direction depends on
the previous loss/gradient values), so the driver loop runs in Python over
FLAT device arrays: the two-loop recursion, directional derivatives, and
parameter writes are jnp expressions XLA executes on-device; only the
scalar loss/convergence checks cross to the host.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..core.engine import no_grad
from ..core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Minimum of the cubic through (x1,f1,g1),(x2,f2,g2), clipped to
    bounds — the standard interpolation step of strong-Wolfe zoom."""
    if bounds is not None:
        lo, hi = bounds
    else:
        lo, hi = min(x1, x2), max(x1, x2)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    sq = d1 * d1 - g1 * g2
    if sq >= 0:
        d2 = np.sqrt(sq)
        if x1 <= x2:
            t = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            t = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(t, lo), hi)
    return (lo + hi) / 2.0


def _strong_wolfe(obj_func, t, d, f, g, gtd, c1=1e-4, c2=0.9,
                  tolerance_change=1e-9, max_ls=25):
    """Line search satisfying the strong Wolfe conditions (sufficient
    decrease + curvature), bracketing then zooming with cubic
    interpolation. ``obj_func(t)`` evaluates loss and flat grad at step
    size t along d. Returns (f_new, g_new, t, n_evals)."""
    d_norm = float(jnp.max(jnp.abs(d)))
    f0, g0, gtd0 = f, g, gtd
    t_prev, f_prev, g_prev, gtd_prev = 0.0, f0, g0, gtd0
    ls_iter = 0
    # --- bracketing phase ---
    while ls_iter < max_ls:
        f_new, g_new = obj_func(t)
        gtd_new = float(jnp.dot(g_new, d))
        ls_iter += 1
        if f_new > f0 + c1 * t * gtd0 or (ls_iter > 1 and f_new >= f_prev):
            bracket = [(t_prev, f_prev, g_prev, gtd_prev),
                       (t, f_new, g_new, gtd_new)]
            break
        if abs(gtd_new) <= -c2 * gtd0:
            return f_new, g_new, t, ls_iter
        if gtd_new >= 0:
            bracket = [(t, f_new, g_new, gtd_new),
                       (t_prev, f_prev, g_prev, gtd_prev)]
            break
        t_next = _cubic_interpolate(t_prev, f_prev, gtd_prev, t, f_new,
                                    gtd_new, bounds=(2 * t, 10 * t))
        t_prev, f_prev, g_prev, gtd_prev = t, f_new, g_new, gtd_new
        t = t_next
    else:
        bracket = [(0.0, f0, g0, gtd0), (t, f_new, g_new, gtd_new)]
    # --- zoom phase ---
    while ls_iter < max_ls:
        lo, hi = (bracket[0], bracket[1]) \
            if bracket[0][1] <= bracket[1][1] else (bracket[1], bracket[0])
        if abs(hi[0] - lo[0]) * d_norm < tolerance_change:
            break
        t = _cubic_interpolate(bracket[0][0], bracket[0][1], bracket[0][3],
                               bracket[1][0], bracket[1][1], bracket[1][3])
        f_new, g_new = obj_func(t)
        gtd_new = float(jnp.dot(g_new, d))
        ls_iter += 1
        if f_new > f0 + c1 * t * gtd0 or f_new >= lo[1]:
            hi_new = (t, f_new, g_new, gtd_new)
            bracket = [lo, hi_new]
        else:
            if abs(gtd_new) <= -c2 * gtd0:
                return f_new, g_new, t, ls_iter
            if gtd_new * (hi[0] - lo[0]) >= 0:
                bracket = [(t, f_new, g_new, gtd_new), lo]
            else:
                bracket = [(t, f_new, g_new, gtd_new), hi]
    lo = bracket[0] if bracket[0][1] <= bracket[1][1] else bracket[1]
    return lo[1], lo[2], lo[0], ls_iter


class LBFGS(Optimizer):
    """``step(closure)`` minimizes the closure's loss with L-BFGS
    (reference API: optimizer/lbfgs.py:307). The closure must
    zero grads, compute the loss, call backward, and return the loss."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn: Optional[str] = None,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                f"line_search_fn must be None or 'strong_wolfe', "
                f"got {line_search_fn!r}")
        self._max_iter = max_iter
        self._max_eval = max_eval if max_eval is not None \
            else max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        self._line_search_fn = line_search_fn
        # global (not per-param) quasi-Newton state over the flat vector
        self._state = {"n_func_evals": 0, "n_iter": 0,
                       "old_sk": [], "old_yk": [], "ro": [],
                       "d": None, "t": None, "prev_flat_grad": None,
                       "H_diag": 1.0}

    # ---- flat-vector plumbing ----
    def _flat_params(self):
        return jnp.concatenate(
            [p._data.astype(jnp.float32).reshape(-1)
             for p in self._parameter_list])

    def _flat_grad(self):
        parts = []
        for p in self._parameter_list:
            if p.grad is None:
                parts.append(jnp.zeros(int(np.prod(p.shape) or 1),
                                       jnp.float32))
            else:
                parts.append(p.grad._data.astype(jnp.float32).reshape(-1))
        return jnp.concatenate(parts)

    def _set_flat_params(self, flat):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape) or 1)
            p._rebind(flat[off:off + n].reshape(p.shape)
                      .astype(p._data.dtype))
            off += n

    def _evaluate(self, closure, x, t, d):
        """Loss and flat grad at x + t*d (params restored by caller)."""
        self._set_flat_params(x + t * d)
        loss = closure()
        return float(loss.numpy() if isinstance(loss, Tensor) else loss), \
            self._flat_grad()

    @no_grad()
    def step(self, closure: Callable = None):  # noqa: C901
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that "
                             "re-evaluates the model and returns the loss")

        from ..core.engine import enable_grad

        def run_closure():
            with enable_grad():
                return closure()

        st = self._state
        lr = self.get_lr()
        loss = run_closure()
        orig_loss = loss
        loss_f = float(loss.numpy() if isinstance(loss, Tensor) else loss)
        st["n_func_evals"] += 1
        current_evals = 1
        flat_grad = self._flat_grad()
        if float(jnp.max(jnp.abs(flat_grad))) <= self._tol_grad:
            return orig_loss

        n_iter = 0
        while n_iter < self._max_iter:
            n_iter += 1
            st["n_iter"] += 1
            # ---- direction: two-loop recursion over (s, y) history ----
            if st["n_iter"] == 1 or st["d"] is None:
                # st["d"] is None when a previous step() broke on the
                # directional-derivative check before ever taking a
                # step — restart from steepest descent instead of
                # dereferencing the never-stored (d, t)
                d = -flat_grad
                st["old_sk"], st["old_yk"], st["ro"] = [], [], []
                st["H_diag"] = 1.0
            else:
                y = flat_grad - st["prev_flat_grad"]
                s = st["d"] * st["t"]
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:
                    if len(st["old_yk"]) >= self._history_size:
                        st["old_yk"].pop(0)
                        st["old_sk"].pop(0)
                        st["ro"].pop(0)
                    st["old_yk"].append(y)
                    st["old_sk"].append(s)
                    st["ro"].append(1.0 / ys)
                    st["H_diag"] = ys / float(jnp.dot(y, y))
                num = len(st["old_yk"])
                q = -flat_grad
                al = [0.0] * num
                for i in range(num - 1, -1, -1):
                    al[i] = float(jnp.dot(st["old_sk"][i], q)) * st["ro"][i]
                    q = q - al[i] * st["old_yk"][i]
                d = q * st["H_diag"]
                for i in range(num):
                    be_i = float(jnp.dot(st["old_yk"][i], d)) * st["ro"][i]
                    d = d + st["old_sk"][i] * (al[i] - be_i)
            st["prev_flat_grad"] = flat_grad

            # ---- step size ----
            if st["n_iter"] == 1:
                t = min(1.0, 1.0 / float(jnp.sum(jnp.abs(flat_grad)))) * lr
            else:
                t = lr
            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self._tol_change:
                break

            if self._line_search_fn == "strong_wolfe":
                x_init = self._flat_params()

                def obj_func(tt):
                    f, g = self._evaluate(run_closure, x_init, tt, d)
                    return f, g

                loss_f, flat_grad, t, ls_evals = _strong_wolfe(
                    obj_func, t, d, loss_f, flat_grad, gtd,
                    tolerance_change=self._tol_change)
                self._set_flat_params(x_init + t * d)
                current_evals += ls_evals
                st["n_func_evals"] += ls_evals
            else:
                self._set_flat_params(self._flat_params() + t * d)
                if n_iter != self._max_iter:
                    loss = run_closure()
                    loss_f = float(loss.numpy()
                                   if isinstance(loss, Tensor) else loss)
                    flat_grad = self._flat_grad()
                    current_evals += 1
                    st["n_func_evals"] += 1
            st["d"], st["t"] = d, t

            # ---- convergence ----
            if current_evals >= self._max_eval:
                break
            if float(jnp.max(jnp.abs(flat_grad))) <= self._tol_grad:
                break
            if float(jnp.max(jnp.abs(d * t))) <= self._tol_change:
                break
        return orig_loss

    # the quasi-Newton state is global over the flat vector, not
    # per-parameter — serialize it wholesale
    def state_dict(self):
        st = self._state
        sd = {"n_func_evals": st["n_func_evals"], "n_iter": st["n_iter"],
              "H_diag": st["H_diag"], "ro": list(st["ro"]),
              "global_step": self._global_step}
        for k in ("old_sk", "old_yk"):
            for i, v in enumerate(st[k]):
                sd[f"{k}_{i}"] = Tensor(v)
        for k in ("d", "prev_flat_grad"):
            if st[k] is not None:
                sd[k] = Tensor(st[k])
        if st["t"] is not None:
            sd["t"] = st["t"]
        return sd

    def set_state_dict(self, sd):
        st = self._state
        st["n_func_evals"] = int(sd.get("n_func_evals", 0))
        st["n_iter"] = int(sd.get("n_iter", 0))
        st["H_diag"] = float(sd.get("H_diag", 1.0))
        st["ro"] = list(sd.get("ro", []))
        self._global_step = int(sd.get("global_step", 0))
        for k in ("old_sk", "old_yk"):
            vals = []
            i = 0
            while f"{k}_{i}" in sd:
                v = sd[f"{k}_{i}"]
                vals.append(v._data if isinstance(v, Tensor)
                            else jnp.asarray(v))
                i += 1
            st[k] = vals
        for k in ("d", "prev_flat_grad"):
            if k in sd:
                v = sd[k]
                st[k] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
        if "t" in sd:
            st["t"] = float(sd["t"])
