"""Optimizer base + SGD family.

TPU-native equivalent of the reference's optimizer stack (reference:
python/paddle/optimizer/optimizer.py — Optimizer base with accumulators,
regularization, grad clip; fused multi-tensor adam kernels
phi/kernels/gpu/adam_kernel.cu). The TPU-first design: every optimizer
defines a pure per-parameter ``_rule`` over raw arrays; ``step()`` applies
it through ONE ``jax.jit``-compiled pytree update (the multi-tensor fused
path — a single XLA program updating all params), with donated buffers so
updates are in-place in HBM.
"""
from __future__ import annotations

import functools
from collections import OrderedDict, defaultdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.engine import no_grad
from ..core.flags import flag
from ..core.tensor import Parameter, Tensor
from ..nn.clip import ClipGradBase
from ..regularizer import WeightDecayRegularizer, L2Decay
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta",
           "RMSProp", "Rprop"]


def _stochastic_round_bf16(x: jnp.ndarray, key) -> jnp.ndarray:
    """fp32 -> bf16 with stochastic rounding (unbiased downcast).

    Adds uniform random bits below the bf16 mantissa cut, then truncates.
    IEEE-754 bit ordering makes the integer add carry correctly through
    mantissa/exponent within a sign class, so E[round(x)] == x. Used for
    master-free low-memory training (bf16 params updated directly); the
    reference's counterpart is the fp32 master-weight path of the fused
    adam kernel (phi/kernels/gpu/adam_kernel.cu multi_precision)."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, x.shape, dtype=jnp.uint16).astype(jnp.uint32)
    rounded = (bits + noise) >> 16
    return jax.lax.bitcast_convert_type(
        rounded.astype(jnp.uint16), jnp.bfloat16)


class Optimizer:
    """Base optimizer.

    ``_rule(p, g, state, hyper) -> (new_p, new_state)`` is the pure update;
    subclasses define it plus ``_init_state(p)``.
    """

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False, stochastic_rounding=False):
        if parameters is None:
            raise ValueError(
                "parameters must be provided (dygraph-style optimizer)")
        if isinstance(parameters, (list, tuple)) and parameters and \
                isinstance(parameters[0], dict):
            self._param_groups = []
            flat = []
            for group in parameters:
                g = dict(group)
                plist = list(g.pop("params"))
                flat.extend(plist)
                g["params"] = plist
                self._param_groups.append(g)
            self._parameter_list = flat
        else:
            self._parameter_list = list(parameters)
            self._param_groups = [{"params": self._parameter_list}]

        self._learning_rate = learning_rate
        if isinstance(weight_decay, float):
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators: Dict[int, Dict[str, Any]] = {}
        self._global_step = 0
        self._jit_update = None
        self._donating_grads = False  # set when the fused update compiles
        self._multi_precision = multi_precision
        self._master_weights: Dict[int, jnp.ndarray] = {}
        # Master-free low-memory mode: bf16 params are upcast to fp32 for
        # the update rule and written back with stochastic rounding — an
        # unbiased downcast, so no fp32 shadow copy is needed. Halves the
        # optimizer footprint vs multi_precision (no 4-byte master).
        self._stochastic_rounding = stochastic_rounding
        # Storage dtype for the heavy per-param moment accumulators
        # (moment1/moment2/velocity...). None = fp32 (reference adam
        # semantics); "bfloat16" stores them in bf16 and upcasts to fp32
        # inside the rule, halving moment memory (the knob that lets
        # GPT-3 1.3B + AdamW fit one 16GB chip).
        self._moment_dtype = None

    # ---------------- lr ----------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._learning_rate = scheduler

    # ---------------- state ----------------
    def _state_for(self, p: Parameter) -> Dict[str, Any]:
        key = id(p)
        if key not in self._accumulators:
            st = self._init_state(p)
            # O2 master weights (reference: multi_precision fused adam —
            # fp32 shadow params for fp16/bf16 models). Moments must be
            # fp32 from step 0: the update rule runs on the fp32 master,
            # so bf16-initialized moments would flip to fp32 after the
            # first step and force a full recompile of the train step.
            if self._multi_precision and p._data.dtype in (jnp.float16,
                                                           jnp.bfloat16):
                exempt = self._lowprec_state_keys()
                st = {k: (v.astype(jnp.float32)
                          if k not in exempt and hasattr(v, "dtype") and
                          jnp.issubdtype(v.dtype, jnp.floating) else v)
                      for k, v in st.items()}
                st["_master"] = p._data.astype(jnp.float32)
            self._accumulators[key] = st
        return self._accumulators[key]

    def _init_state(self, p: Parameter) -> Dict[str, Any]:
        return {}

    def _lowprec_state_keys(self) -> frozenset:
        """State keys deliberately stored below fp32 (see _moment_dtype);
        exempt from the multi_precision fp32 upcast in _state_for."""
        return frozenset()

    def _moment_store(self, arr: jnp.ndarray) -> jnp.ndarray:
        """Downcast a moment accumulator to its storage dtype."""
        if self._moment_dtype is not None:
            return arr.astype(self._moment_dtype)
        return arr

    def _hyper(self) -> Dict[str, Any]:
        """Scalar hyperparams fed to the compiled rule each step."""
        h = {"lr": self.get_lr()}
        if self._stochastic_rounding:
            h["_sr_key"] = jax.random.PRNGKey(self._global_step)
        return h

    def _rule(self, p, g, state, hyper):
        raise NotImplementedError

    # ---------------- step ----------------
    def _collect_params_grads(self) -> List[Tuple[Parameter, Optional[Tensor]]]:
        out = []
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            out.append((p, p.grad))
        return out

    @no_grad()
    def step(self):
        params_grads = [(p, g) for p, g in self._collect_params_grads()
                        if g is not None]
        if not params_grads:
            self._global_step += 1
            return
        self._apply_optimize(params_grads)
        self._global_step += 1

    def _apply_optimize(self, params_grads):
        # per-parameter lr scaling / regularization (python side, cheap)
        if self._weight_decay is not None:
            new_pg = []
            for p, g in params_grads:
                if isinstance(self._weight_decay, WeightDecayRegularizer) and \
                        p.regularizer is None and not self._decoupled_wd():
                    g = Tensor(self._weight_decay(p._data, g._data))
                elif p.regularizer is not None:
                    g = Tensor(p.regularizer(p._data, g._data))
                new_pg.append((p, g))
            params_grads = new_pg
        elif any(p.regularizer is not None for p, _ in params_grads):
            params_grads = [
                (p, Tensor(p.regularizer(p._data, g._data))
                 if p.regularizer is not None else g)
                for p, g in params_grads]

        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)

        hyper = self._hyper()
        params = [p for p, _ in params_grads]
        p_arrays = [p._data for p in params]
        g_arrays = [g._data for _, g in params_grads]
        states = [self._state_for(p) for p in params]
        if self._multi_precision:
            # lazy O2 master creation: restored state without a saved
            # master gets one derived from the (by now restored) param
            for p, st in zip(params, states):
                if "_master" not in st and p._data.dtype in (
                        jnp.float16, jnp.bfloat16):
                    st["_master"] = p._data.astype(jnp.float32)
        per_param = [self._per_param_hyper(p) for p in params]

        new_ps, new_states = self._fused_update(
            p_arrays, g_arrays, states, hyper, per_param)
        for p, np_, ns in zip(params, new_ps, new_states):
            p._rebind(np_)
            self._accumulators[id(p)] = ns
        if self._donating_grads:
            # gradient buffers were donated to (consumed by) the fused
            # update — drop the now-dead Tensors so nothing can read them
            for p in params:
                p.grad = None

    def _decoupled_wd(self) -> bool:
        return False

    def _per_param_hyper(self, p: Parameter) -> Dict[str, float]:
        return {"lr_mult": p.optimize_attr.get("learning_rate", 1.0)}

    def _update_arrays(self, ps, gs, sts, hyp, pps):
        """Pure pytree update over raw arrays — usable both from the eager
        jitted path and traced inside a whole-step compiled program."""
        new_ps, new_sts = [], []
        sr_key = hyp.get("_sr_key") if isinstance(hyp, dict) else None
        for i, (p, g, st, pp) in enumerate(zip(ps, gs, sts, pps)):
            h = {k: v for k, v in hyp.items() if k != "_sr_key"}
            h.update(pp)
            h["lr"] = h["lr"] * h.pop("lr_mult", 1.0)
            st = dict(st)
            master = st.pop("_master", None)
            p_eff = master if master is not None else p
            sr = (sr_key is not None and master is None
                  and p.dtype == jnp.bfloat16)
            if sr:  # master-free: fp32 compute, unbiased bf16 writeback
                p_eff = p.astype(jnp.float32)
            g_eff = g.astype(p_eff.dtype) if g.dtype != p_eff.dtype else g
            np_, nst = self._rule(p_eff, g_eff, st, h)
            if master is not None:
                nst = dict(nst)
                nst["_master"] = np_
            if sr:
                new_ps.append(_stochastic_round_bf16(
                    np_, jax.random.fold_in(sr_key, i)))
            else:
                new_ps.append(np_.astype(p.dtype))
            new_sts.append(nst)
        return new_ps, new_sts

    def _fused_update(self, p_arrays, g_arrays, states, hyper, per_param):
        """One compiled XLA program updating every parameter (the fused
        multi-tensor path); cached by pytree structure via jax.jit.
        Parameter and accumulator buffers are always donated (updated in
        place in HBM); with ``FLAGS_optimizer_donate_grads`` the gradient
        buffers are donated too — step() then consumes the grads
        (``p.grad`` comes back None), removing the step's transient
        per-parameter gradient copy."""
        if self._jit_update is None:
            self._donating_grads = flag("optimizer_donate_grads")
            donate = (0, 1, 2) if self._donating_grads else (0, 2)
            self._jit_update = functools.partial(
                jax.jit, donate_argnums=donate)(self._update_arrays)
        return self._jit_update(p_arrays, g_arrays, states, hyper, per_param)

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    @no_grad()
    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, None

    # ---------------- checkpointing ----------------
    def state_dict(self):
        sd = OrderedDict()
        for p in self._parameter_list:
            st = self._accumulators.get(id(p))
            if not st:
                continue
            for k, v in st.items():
                if isinstance(v, jnp.ndarray) or hasattr(v, "shape"):
                    # COPY: the live accumulator buffers are donated to
                    # the next fused update — a checkpoint that aliases
                    # them would be deleted by the following step()
                    sd[f"{p.name}_{k}"] = Tensor(jnp.array(v, copy=True))
                else:
                    sd[f"{p.name}_{k}"] = v
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["global_step"] = self._global_step
        return sd

    def set_state_dict(self, state_dict):
        import warnings

        self._global_step = int(state_dict.get("global_step", 0))
        consumed = {"global_step"}
        if "LR_Scheduler" in state_dict:
            consumed.add("LR_Scheduler")
            if isinstance(self._learning_rate, LRScheduler):
                self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for p in self._parameter_list:
            st = self._init_state(p)
            found = False
            missing = []
            for k in list(st.keys()):
                sk = f"{p.name}_{k}"
                if sk in state_dict:
                    v = state_dict[sk]
                    v = v._data if isinstance(v, Tensor) else v
                    # copy arrays: the restored state will be donated by
                    # step(); never let that delete the caller's dict
                    if hasattr(v, "shape") and hasattr(v, "dtype"):
                        v = jnp.array(v, copy=True)
                    st[k] = v
                    consumed.add(sk)
                    found = True
                else:
                    missing.append(sk)
            # fp32 master weights from multi_precision (O2) runs are keyed
            # "{name}__master" (state key "_master" never appears in
            # _init_state, so restore it explicitly; re-derive from the
            # param when absent so resumed O2 training keeps a master)
            mk = f"{p.name}__master"
            if mk in state_dict:
                v = state_dict[mk]
                st["_master"] = v._data if isinstance(v, Tensor) \
                    else jnp.asarray(v)
                consumed.add(mk)
                found = True
            # when the checkpoint lacks a master, _apply_optimize derives
            # one lazily at the first step — after model weights load, so
            # a stale pre-restore param value is never captured
            if found:
                self._accumulators[id(p)] = st
                if missing:
                    warnings.warn(
                        f"optimizer state for '{p.name}' partially restored;"
                        f" missing keys: {missing}")
        unexpected = [k for k in state_dict if k not in consumed]
        if unexpected:
            warnings.warn(
                f"optimizer set_state_dict: unexpected keys {unexpected[:8]}"
                + ("..." if len(unexpected) > 8 else ""))

    set_dict = set_state_dict


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _rule(self, p, g, state, hyper):
        return p - hyper["lr"] * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._data)}

    def _rule(self, p, g, state, hyper):
        v = self._momentum * state["velocity"] + g
        if self._use_nesterov:
            new_p = p - hyper["lr"] * (g + self._momentum * v)
        else:
            new_p = p - hyper["lr"] * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._data, self._init_val)}

    def _rule(self, p, g, state, hyper):
        m = state["moment"] + g * g
        new_p = p - hyper["lr"] * g / (jnp.sqrt(m) + self._epsilon)
        return new_p, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p._data),
                "avg_squared_update": jnp.zeros_like(p._data)}

    def _rule(self, p, g, state, hyper):
        rho, eps = self._rho, self._epsilon
        asg = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        update = g * jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * update * update
        return p - hyper["lr"] * update, \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class Rprop(Optimizer):
    """Resilient backpropagation (reference:
    python/paddle/optimizer/rprop.py:28, kernel
    phi/kernels/impl/rprop_kernel_impl.h). Sign-based updates with a
    per-element step size: agreeing consecutive gradient signs grow the
    step by eta+ (capped at lr_range[1]), disagreeing signs shrink it
    by eta- (floored at lr_range[0]) and suppress that element's update
    for the step. ``learning_rate`` seeds the per-element step sizes;
    the rule never reads the scalar lr again."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name, multi_precision)
        self._lr_min, self._lr_max = map(float, learning_rate_range)
        self._eta_n, self._eta_p = map(float, etas)
        if not (0.0 < self._eta_n < 1.0 < self._eta_p):
            raise ValueError(f"etas must satisfy 0<eta-<1<eta+: {etas}")

    def _init_state(self, p):
        return {"prev_grad": jnp.zeros_like(p._data),
                "learning_rate": jnp.full(p._data.shape,
                                          self.get_lr(), p._data.dtype)}

    def _rule(self, p, g, state, hyper):
        prod = g * state["prev_grad"]
        lr = state["learning_rate"].astype(p.dtype)
        lr = jnp.where(prod > 0,
                       jnp.minimum(lr * self._eta_p, self._lr_max),
                       jnp.where(prod < 0,
                                 jnp.maximum(lr * self._eta_n,
                                             self._lr_min), lr))
        g_eff = jnp.where(prod < 0, jnp.zeros_like(g), g)
        new_p = p - lr * jnp.sign(g_eff)
        return new_p, {"prev_grad": g_eff,
                       "learning_rate": lr.astype(p.dtype)}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p._data),
              "momentum": jnp.zeros_like(p._data)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p._data)
        return st

    def _rule(self, p, g, state, hyper):
        rho, eps = self._rho, self._epsilon
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state["momentum"] + hyper["lr"] * g / denom
        new_state = {"mean_square": ms, "momentum": mom}
        if self._centered:
            new_state["mean_grad"] = mg
        return p - mom, new_state
