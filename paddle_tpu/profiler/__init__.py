from . import alerts, memory, roofline, stats, timeseries  # noqa: F401
from .alerts import AlertEngine, Rule, default_rules  # noqa: F401
from .profiler import (  # noqa: F401
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, dump_rank,
    export_chrome_tracing, load_profiler_result, make_scheduler,
    start_span_capture, stop_span_capture,
)
from .timer import Benchmark, benchmark  # noqa: F401
from .timeseries import TimeSeriesSampler  # noqa: F401

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing",
           "load_profiler_result", "Benchmark", "benchmark", "stats",
           "roofline", "memory", "dump_rank", "timeseries", "alerts",
           "TimeSeriesSampler", "AlertEngine", "Rule", "default_rules",
           "start_span_capture", "stop_span_capture"]
