"""Fleet alerting: a small rule engine evaluated per telemetry tick.

Rules read the :class:`profiler.timeseries.TimeSeriesSampler` rings
(latest gauge level, counter delta rate, trailing rate distribution)
and classify each tick as breach / clear. A rule FIRES after
``for_ticks`` consecutive breaches (sustained-window semantics — one
noisy tick never pages) and RESOLVES on the first clear tick. Both
transitions are journaled as ``alert`` lifecycle events (PR 9 flight
recorder) and counted under ``alert.{fired,resolved}`` with the live
count on the ``alert.active`` gauge, so the alert trail survives in
every artifact tier: journal JSONL, stats snapshot, telemetry series,
and ``serve_top --history``.

Rule kinds:

- ``value`` — compare the metric's latest level (gauge value /
  histogram count) against the threshold;
- ``rate``  — compare the counter's latest delta rate (events/s);
- ``spike`` — compare the counter's latest delta rate against
  ``scale ×`` the trailing-window mean rate (relative burst
  detection: preemption storms, fault storms).

Thresholds may be numbers or ANOTHER METRIC NAME (resolved against
the same tick, scaled by ``scale``) — that is how
``hbm.bytes_in_use > 0.9 * hbm.bytes_limit`` and
``fleet.replicas_alive < fleet.replicas`` stay correct whatever the
device or fleet size.

Stdlib-only at import (the stats import is lazy and guarded) so the
tools can load it standalone alongside timeseries.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

__all__ = ["Rule", "AlertEngine", "default_rules"]


@dataclass(frozen=True)
class Rule:
    """One alert rule. ``threshold`` is a number or a metric name
    (resolved per tick); ``scale`` multiplies a metric-name threshold
    (``0.9 * hbm.bytes_limit``) or, for ``kind="spike"``, the trailing
    mean rate. ``for_ticks`` is the sustained-window length."""

    name: str
    metric: str
    op: str = ">"                    # ">" or "<"
    threshold: Union[float, str] = 0.0
    scale: float = 1.0
    kind: str = "value"              # "value" | "rate" | "spike"
    for_ticks: int = 1

    def __post_init__(self):
        if self.op not in (">", "<"):
            raise ValueError(f"rule {self.name}: op must be > or <")
        if self.kind not in ("value", "rate", "spike"):
            raise ValueError(f"rule {self.name}: bad kind "
                             f"{self.kind!r}")
        if self.for_ticks < 1:
            raise ValueError(f"rule {self.name}: for_ticks >= 1")


def default_rules(n_replicas: Optional[int] = None) -> List[Rule]:
    """The ISSUE's standing rule set. ``fleet-replica-down`` compares
    alive against the registered ``fleet.replicas`` gauge, so it holds
    for any fleet size; pass ``n_replicas`` to pin a literal floor
    instead."""
    rules = [
        Rule("slo-burn", "slo.burn_rate", ">", 2.0, for_ticks=3),
        Rule("hbm-pressure", "hbm.bytes_in_use", ">",
             "hbm.bytes_limit", scale=0.9),
        Rule("preemption-spike", "serving.preemptions", ">",
             kind="spike", scale=3.0, for_ticks=1),
        # one tenant persistently consuming >80% of attributed device
        # time (serving/accounting.py tenant.max_share gauge, ISSUE
        # 17) — the multi-tenant hog signal; absent gauge (ledger
        # off / single tenant run idle) never fires
        Rule("tenant-hog", "tenant.max_share", ">", 0.8,
             for_ticks=3),
    ]
    if n_replicas is not None:
        rules.append(Rule("fleet-replica-down", "fleet.replicas_alive",
                          "<", float(n_replicas)))
    else:
        rules.append(Rule("fleet-replica-down", "fleet.replicas_alive",
                          "<", "fleet.replicas"))
    return rules


@dataclass
class _RuleState:
    streak: int = 0
    firing: bool = False


class AlertEngine:
    """Evaluate a rule list against a sampler, once per tick.

    ``active`` maps firing rule name -> the fire record; ``history``
    keeps every fire/resolve transition (tests and serve_top read
    it). Pass a :class:`serving.journal.FlightRecorder` to journal
    transitions as ``alert`` lifecycle events.
    """

    def __init__(self, rules: Optional[List[Rule]] = None,
                 journal=None):
        self.rules = list(rules) if rules is not None \
            else default_rules()
        self.journal = journal
        self.active: Dict[str, dict] = {}
        self.history: List[dict] = []
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}

    # ------------------------------------------------------------

    def _threshold(self, rule: Rule, sampler) -> Optional[float]:
        if isinstance(rule.threshold, str):
            ref = sampler.value(rule.threshold)
            if ref is None:
                return None
            return rule.scale * ref
        return float(rule.threshold)

    def _reading(self, rule: Rule, sampler):
        """(value, threshold) for this tick, or None when the metric
        has not been seen yet (absent metrics never breach)."""
        if rule.kind == "value":
            v = sampler.value(rule.metric)
            thr = self._threshold(rule, sampler)
        elif rule.kind == "rate":
            v = sampler.rate(rule.metric)
            thr = self._threshold(rule, sampler)
        else:  # spike: latest rate vs scale x trailing mean rate
            rates = sampler.rates(rule.metric)
            if len(rates) < 2:
                return None
            v = rates[-1]
            trailing = rates[:-1]
            thr = rule.scale * (sum(trailing) / len(trailing))
        if v is None or thr is None:
            return None
        return v, thr

    def evaluate(self, sampler) -> List[dict]:
        """One pass over the rules; returns this tick's transitions
        (fire + resolve records)."""
        transitions: List[dict] = []
        for rule in self.rules:
            st = self._state[rule.name]
            reading = self._reading(rule, sampler)
            breach = False
            value = thr = None
            if reading is not None:
                value, thr = reading
                breach = value > thr if rule.op == ">" else value < thr
            if breach:
                st.streak += 1
                if not st.firing and st.streak >= rule.for_ticks:
                    st.firing = True
                    transitions.append(
                        self._transition(rule, "firing", value, thr))
            else:
                st.streak = 0
                if st.firing:
                    st.firing = False
                    transitions.append(
                        self._transition(rule, "resolved", value, thr))
        return transitions

    def _transition(self, rule: Rule, state: str, value, thr) -> dict:
        rec = {"name": rule.name, "metric": rule.metric,
               "state": state,
               "value": None if value is None else round(value, 6),
               "threshold": None if thr is None else round(thr, 6)}
        if state == "firing":
            self.active[rule.name] = rec
        else:
            self.active.pop(rule.name, None)
        self.history.append(rec)
        if self.journal is not None:
            try:
                self.journal.record("alert", extra=rec)
            except Exception:
                pass
        try:
            from . import stats as _stats

            _stats.inc("alert.fired" if state == "firing"
                       else "alert.resolved")
            _stats.set_gauge("alert.active", len(self.active))
        except Exception:
            pass
        return rec
