"""HBM memory telemetry: runtime allocator counters + live-buffer census.

TPU-native equivalent of the reference's memory view (SURVEY §1 layer 3:
profiler_statistic.py's device/memory tables fed by its own allocator
stats, memory/stats.h). PJRT owns HBM here, so the source of truth is
``device.memory_stats()`` (bytes_in_use, peak_bytes_in_use, bytes_limit)
plus a census of the process's live jax arrays — which buffers are
actually pinned, by dtype and by largest shape.

Published as ``hbm.*`` gauges in the ``profiler.stats`` registry:

- ``hbm.bytes_in_use`` / ``hbm.peak_bytes_in_use`` / ``hbm.bytes_limit``
  straight from the PJRT allocator (0 on backends that expose none,
  e.g. CPU);
- ``hbm.utilization``  bytes_in_use / bytes_limit;
- ``hbm.live_buffers`` / ``hbm.live_bytes``  live-array census (works
  on every backend — on CPU this is the only populated part).

``Profiler`` samples this module at start/step/stop boundaries, so the
``hbm.*`` gauges land in the chrome-trace counter timeline alongside
the op spans, and ``summary()`` prints the peak watermark.
"""
from __future__ import annotations

from typing import Optional

import jax

from . import stats as _stats

__all__ = ["hbm_stats", "live_buffer_census", "sample", "watermark"]


def hbm_stats(device=None) -> dict:
    """Raw PJRT allocator counters for the device ({} when the backend
    exposes none — CPU returns None from memory_stats)."""
    try:
        if device is None:
            device = jax.devices()[0]
        return dict(device.memory_stats() or {})
    except Exception:
        return {}


def live_buffer_census(max_shapes: int = 8) -> dict:
    """Census of the process's live jax arrays: total count/bytes,
    bytes by dtype, and the ``max_shapes`` largest (shape, dtype)
    groups by resident bytes. Committed-but-deleted buffers are
    skipped (a donated array stays in ``live_arrays`` briefly)."""
    by_dtype: dict = {}
    by_shape: dict = {}
    count = 0
    total = 0
    try:
        arrays = jax.live_arrays()
    except Exception:
        arrays = []
    for a in arrays:
        try:
            if getattr(a, "is_deleted", lambda: False)():
                continue
            nbytes = int(a.nbytes)
            key = str(a.dtype)
            shape_key = f"{key}{list(a.shape)}"
        except Exception:
            continue
        count += 1
        total += nbytes
        by_dtype[key] = by_dtype.get(key, 0) + nbytes
        agg = by_shape.setdefault(shape_key, [0, 0])
        agg[0] += 1
        agg[1] += nbytes
    top = sorted(by_shape.items(), key=lambda kv: -kv[1][1])[:max_shapes]
    return {
        "count": count,
        "bytes": total,
        "by_dtype": dict(sorted(by_dtype.items(), key=lambda kv: -kv[1])),
        "top_shapes": [{"shape": k, "count": c, "bytes": b}
                       for k, (c, b) in top],
    }


def sample(device=None, census: bool = True) -> dict:
    """One telemetry sample: read the allocator counters (and optionally
    the live-buffer census), publish the ``hbm.*`` gauges, and return
    the combined dict. Safe to call on any backend at any time."""
    stats = hbm_stats(device)
    in_use = int(stats.get("bytes_in_use", 0))
    peak = int(stats.get("peak_bytes_in_use", 0))
    limit = int(stats.get("bytes_limit", 0))
    out = {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
           "bytes_limit": limit}
    _stats.set_gauge("hbm.bytes_in_use", in_use)
    _stats.set_gauge("hbm.peak_bytes_in_use", peak)
    _stats.set_gauge("hbm.bytes_limit", limit)
    if limit:
        _stats.set_gauge("hbm.utilization", in_use / limit)
        out["utilization"] = in_use / limit
    if census:
        c = live_buffer_census()
        _stats.set_gauge("hbm.live_buffers", c["count"])
        _stats.set_gauge("hbm.live_bytes", c["bytes"])
        out["live"] = c
    return out


def watermark(device=None) -> Optional[dict]:
    """Peak-watermark view for ``Profiler.summary()``: fresh allocator
    peak vs limit, falling back to the live-buffer census on backends
    without allocator counters. None when there is nothing to show."""
    stats = hbm_stats(device)
    peak = int(stats.get("peak_bytes_in_use", 0))
    limit = int(stats.get("bytes_limit", 0))
    if peak:
        return {"source": "pjrt",
                "peak_bytes_in_use": peak,
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "bytes_limit": limit,
                "peak_pct_of_limit": (100.0 * peak / limit
                                      if limit else None)}
    census = live_buffer_census(max_shapes=4)
    if census["count"]:
        return {"source": "live_arrays",
                "bytes_in_use": census["bytes"],
                "live_buffers": census["count"],
                "top_shapes": census["top_shapes"]}
    return None
