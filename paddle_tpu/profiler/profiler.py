"""Profiler: spans, scheduler windows, chrome-trace export.

TPU-native equivalent of the reference's profiler (reference:
python/paddle/profiler/profiler.py — ``Profiler`` with states
``profiler.py:79``, window scheduler ``make_scheduler``, chrome trace
``export_chrome_tracing:215``; C++ host tracer
platform/profiler/host_tracer.cc RecordEvent spans). Two layers:

- host spans: ``RecordEvent`` context managers collected into a tree,
  exported in the chrome-trace JSON format the reference emits;
- device trace: ``jax.profiler`` start/stop around the profiled window
  (XLA's own profiler session → TensorBoard/XPlane dump directory);
- runtime counters: the process-wide ``profiler.stats`` registry
  (per-op dispatch counts, VJP-cache hits, compile histograms, pool
  gauges) is sampled at start/step/stop into chrome-trace counter
  events (``"ph": "C"``) and folded into ``summary()``.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, List, Optional

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing",
           "load_profiler_result", "dump_rank",
           "start_span_capture", "stop_span_capture"]


def _process_index() -> int:
    """Rank of this process (0 when jax is uninitialized): stamps trace
    metadata, worker names, and fleet snapshots so multi-host runs stay
    distinguishable after merging."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class ProfilerState(Enum):
    """(profiler.py:79)"""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    """(profiler.py:99) — CPU=host spans, GPU→TPU device trace."""
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class _SpanStore(threading.local):
    def __init__(self):
        self.events: List[dict] = []
        self.enabled = False


_SPANS = _SpanStore()

# Cross-thread span sinks: ``_SPANS`` is thread-local by design (the
# Profiler lifecycle owns the calling thread's spans), which silently
# drops RecordEvent spans emitted from BACKGROUND threads — the async
# migration streamer, replica step threads. ``start_span_capture``
# registers a process-wide sink every thread's ``RecordEvent.end``
# appends into, so a trace test (or a fleet timeline) can observe
# concurrent spans from all threads with wall-clock-comparable ``ts``.
_SINK_LOCK = threading.Lock()
_SINKS: List[List[dict]] = []


def start_span_capture() -> List[dict]:
    """Begin capturing RecordEvent spans from ALL threads into the
    returned list (chrome-trace "X" dicts, appended live). Sinks stack:
    each capture sees every span ended while it is registered."""
    sink: List[dict] = []
    with _SINK_LOCK:
        _SINKS.append(sink)
    return sink


def stop_span_capture(sink: List[dict]) -> List[dict]:
    """Unregister a ``start_span_capture`` sink and return it."""
    with _SINK_LOCK:
        try:
            _SINKS.remove(sink)
        except ValueError:
            pass
    return sink


class RecordEvent:
    """Host span (reference RecordEvent, event_tracing.h): context
    manager / begin-end pair collected into the chrome trace."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()

    def end(self):
        if self._t0 is None or not (_SPANS.enabled or _SINKS):
            return
        t1 = time.perf_counter_ns()
        ev = {
            "name": self.name, "ph": "X", "pid": os.getpid(),
            "tid": threading.get_ident() % 2 ** 31,
            "ts": self._t0 / 1e3, "dur": (t1 - self._t0) / 1e3,
            "cat": "host",
        }
        if _SPANS.enabled:
            _SPANS.events.append(ev)
        if _SINKS:
            with _SINK_LOCK:
                for s in _SINKS:
                    s.append(ev)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    """(profiler.py make_scheduler): step → ProfilerState window fn."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_on_trace_ready(prof: "Profiler"):
    d = prof.log_dir or "./profiler_log"
    os.makedirs(d, exist_ok=True)
    prof.export(os.path.join(
        d, f"paddle_tpu_trace_{int(time.time())}.json"))


class Profiler:
    """(profiler.py Profiler parity)."""

    def __init__(self, *, targets=None, scheduler=None,
                 on_trace_ready=None, timer_only: bool = False,
                 log_dir: Optional[str] = None):
        self.targets = list(targets or [ProfilerTarget.CPU])
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo,
                                       repeat=1)
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready or _default_on_trace_ready
        self.timer_only = timer_only
        self.log_dir = log_dir
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._events: List[dict] = []
        self._device_active = False
        from .timer import Benchmark

        self.benchmark = Benchmark()

    # ---- device (XLA) session ----
    def _device_start(self):
        if self.timer_only or self._device_active:
            return
        want_device = any(t in (ProfilerTarget.GPU, ProfilerTarget.TPU,
                                ProfilerTarget.CUSTOM_DEVICE)
                          for t in self.targets)
        if not want_device:
            return
        try:
            import jax.profiler

            d = self.log_dir or "./profiler_log"
            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
            self._device_active = True
        except Exception:
            self._device_active = False

    def _device_stop(self):
        if self._device_active:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_active = False

    # ---- runtime-counter sampling (profiler.stats -> "ph": "C") ----
    def _sample_counters(self):
        """One chrome-trace counter event per live stats metric — the
        counter timeline interleaves with the "X" spans in the same
        exported file (the reference emits device counters the same
        way through its chrome-trace serializer). HBM telemetry is
        refreshed first so the ``hbm.*`` gauges ride the same timeline
        (memory sampled at step boundaries, reference memory view)."""
        from . import memory, stats

        try:
            memory.sample()
        except Exception:
            pass
        snap = stats.snapshot()
        ts = time.perf_counter_ns() / 1e3
        pid = os.getpid()
        for name, val in {**snap["counters"], **snap["gauges"]}.items():
            self._events.append({
                "name": name, "ph": "C", "pid": pid, "tid": 0,
                "ts": ts, "cat": "counter", "args": {"value": val},
            })

    # ---- lifecycle ----
    def start(self):
        self.benchmark.begin()
        _SPANS.enabled = True
        _SPANS.events = []
        self.state = self.scheduler(self.step_num) if self.scheduler \
            else ProfilerState.RECORD
        if self.state in (ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN):
            self._device_start()
        self._sample_counters()
        return self

    def stop(self):
        self._device_stop()
        _SPANS.enabled = False
        self._events.extend(_SPANS.events)
        _SPANS.events = []
        self._sample_counters()
        self.state = ProfilerState.CLOSED
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples: int = 1, sync_value=None):
        self.benchmark.step(num_samples, sync_value=sync_value)
        self._events.extend(_SPANS.events)
        _SPANS.events = []
        self._sample_counters()
        self.step_num += 1
        if self.scheduler is None:
            return
        new = self.scheduler(self.step_num)
        if new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                and self.state not in (ProfilerState.RECORD,
                                       ProfilerState.RECORD_AND_RETURN):
            self._device_start()
        if new == ProfilerState.CLOSED and self._device_active:
            self._device_stop()
        self.state = new

    def step_info(self, unit: str = "samples") -> str:
        return self.benchmark.step_info(unit)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ---- export ----
    def export(self, path: str, format: str = "json"):
        """(export_chrome_tracing:215): chrome-trace JSON. The
        ``metadata`` block stamps the producing rank/pid so
        tools/trace_merge.py can fold per-rank traces into one
        fleet timeline without relying on filenames."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms",
                       "metadata": {"process_index": _process_index(),
                                    "pid": os.getpid()}}, f)
        return path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregate span table (profiler_statistic.py parity): per-name
        count / total / avg / max over the recorded "X" spans (the auto
        ``op::`` dispatch spans give per-op call counts for free), plus
        a cache section reading the stats registry (VJP-cache hit rate,
        jit tracings) — the counters that distinguish a retrace storm
        from steady cache hits. Returns ``{name: [total_ms, calls]}``."""
        agg = {}
        maxes = {}
        for e in self._events:
            if e.get("ph") != "X":
                continue
            a = agg.setdefault(e["name"], [0.0, 0])
            a[0] += e["dur"] / 1e3
            a[1] += 1
            maxes[e["name"]] = max(maxes.get(e["name"], 0.0),
                                   e["dur"] / 1e3)
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}"
                 f"{'Avg(ms)':>12}{'Max(ms)':>12}"]
        for name, (tot, cnt) in sorted(agg.items(), key=lambda x: -x[1][0]):
            lines.append(f"{name:<40}{cnt:>8}{tot:>12.3f}"
                         f"{tot / cnt:>12.3f}{maxes[name]:>12.3f}")
        from . import stats

        hit_rate = stats.vjp_cache_hit_rate()
        cache_lines = ["", f"{'Cache / compile counters':<40}"]
        if hit_rate is not None:
            cache_lines.append(
                f"{'vjp_cache hit rate':<40}"
                f"{100 * hit_rate:>11.1f}%"
                f"  (hit={stats.counter('vjp_cache.hit').value}"
                f" miss={stats.counter('vjp_cache.miss').value}"
                f" admit={stats.counter('vjp_cache.admit').value}"
                f" blocklisted="
                f"{stats.counter('vjp_cache.blocklisted').value})")
        for cname in ("jit.trace", "jit.cache_hit"):
            v = stats.counter(cname).value
            if v:
                cache_lines.append(f"{cname:<40}{v:>8}")
        for hname in ("compile.vjp_trace_us", "compile.vjp_build_us"):
            h = stats.histogram(hname)
            if h.count:
                cache_lines.append(
                    f"{hname:<40}{h.count:>8}{h.total / 1e3:>12.3f}"
                    f"{h.avg / 1e3:>12.3f}{(h.max or 0) / 1e3:>12.3f}")
        extra_lines = self._roofline_lines() + self._hbm_lines()
        out = "\n".join(lines + (cache_lines
                                 if len(cache_lines) > 2 else [])
                        + extra_lines)
        print(out)
        return agg

    @staticmethod
    def _roofline_lines():
        """Per-program cost-model roofline section (programs recorded by
        the jit layers via profiler.roofline)."""
        from . import roofline

        text = roofline.format_report()
        if not text:
            return []
        return ["", f"{'Roofline (XLA cost model)':<40}"] + text.split("\n")

    @staticmethod
    def _hbm_lines():
        """HBM peak-watermark section: allocator peak vs limit (PJRT),
        or the live-buffer census on backends without counters."""
        from . import memory

        try:
            wm = memory.watermark()
        except Exception:
            wm = None
        if not wm:
            return []
        lines = ["", f"{'HBM memory watermark':<40}"]
        if wm["source"] == "pjrt":
            pct = wm.get("peak_pct_of_limit")
            lines.append(
                f"{'peak_bytes_in_use':<40}"
                f"{wm['peak_bytes_in_use'] / 2**30:>11.3f}GiB"
                + (f"  ({pct:.1f}% of limit)" if pct is not None else ""))
            lines.append(f"{'bytes_in_use':<40}"
                         f"{wm['bytes_in_use'] / 2**30:>11.3f}GiB")
            if wm.get("bytes_limit"):
                lines.append(f"{'bytes_limit':<40}"
                             f"{wm['bytes_limit'] / 2**30:>11.3f}GiB")
        else:
            lines.append(f"{'live buffers':<40}{wm['live_buffers']:>8}"
                         f"{wm['bytes_in_use'] / 2**20:>12.3f}MiB")
            for s in wm.get("top_shapes", [])[:3]:
                lines.append(f"  {s['shape']:<38}{s['count']:>8}"
                             f"{s['bytes'] / 2**20:>12.3f}MiB")
        return lines


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """(profiler.py export_chrome_tracing:215): returns an
    on_trace_ready callback writing into ``dir_name``.

    The default worker name includes ``jax.process_index()`` — a plain
    ``host_{pid}`` collides when two hosts of a multi-host run land the
    same pid and write into a shared run dir."""
    def handler(prof: Profiler):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"rank{_process_index()}_host_{os.getpid()}"
        prof.export(os.path.join(
            dir_name, f"{name}_time_{int(time.time())}"
                      f".paddle_trace.json"))

    return handler


def dump_rank(run_dir: str, profiler: "Profiler" = None) -> dict:
    """Write THIS rank's observability artifacts into a shared run dir:

    - ``stats_rank{i}.json`` — ``stats.snapshot()`` (rank-stamped meta)
      with a fresh HBM sample folded in first;
    - ``trace_rank{i}.json`` — the given profiler's chrome trace, when
      one is passed.

    Every rank of a multiproc run calls this with the SAME ``run_dir``
    (each writes only its own files — no cross-rank coordination), then
    ``tools/trace_merge.py RUN_DIR`` folds the rank files into one
    merged trace + one fleet stats snapshot. Returns the paths written.
    """
    from . import memory, stats

    os.makedirs(run_dir, exist_ok=True)
    rank = _process_index()
    try:
        memory.sample()
    except Exception:
        pass
    out = {}
    stats_path = os.path.join(run_dir, f"stats_rank{rank}.json")
    with open(stats_path, "w") as f:
        json.dump(stats.snapshot(), f)
    out["stats"] = stats_path
    if profiler is not None:
        out["trace"] = profiler.export(
            os.path.join(run_dir, f"trace_rank{rank}.json"))
    return out


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)
