"""XLA cost-model roofline analysis: measured wall time vs hardware peaks.

Device-level observability the host-side tracer cannot provide (SURVEY
§1 layer 1/3: the reference derives per-op statistic tables and
device/memory views from its tracer, profiler_statistic.py). On TPU the
compiler already knows every program's arithmetic and memory traffic —
``compiled.cost_analysis()`` reports FLOPs and bytes accessed straight
from XLA's cost model — so instead of asserting "decode runs at 35% of
the weight-bandwidth roofline" from a hand-derived byte count, every
compiled program records its model-derived cost here and any honest
wall-time measurement turns it into achieved FLOP/s, achieved bytes/s,
MFU, and %-of-bandwidth-roofline.

Three cooperating pieces:

- ``record_program(name, compiled)`` — read the XLA cost model of a
  compiled executable into the per-program table and the
  ``compile.{flops,bytes}`` stats gauges. The jit layers
  (jit/static_function.py, jit/train_step.py) and the inference decode
  step call this automatically at compile time via ``AotProgram``.
- ``analyze(name, wall_s)`` — fold a measured wall time into achieved
  rates against the device peak table (TPU generations + CPU fallback,
  env-overridable) and publish ``roofline.*`` gauges.
- ``AotProgram`` — a thin wrapper that turns a ``jax.jit`` function
  into an explicitly compiled executable (``lower().compile()``) so the
  cost model is captured WITHOUT a second compilation; falls back to
  the plain jitted call path on any AOT mismatch.

Honesty note: rates are only as good as the wall time fed in. The jit
layers observe per-call dispatch wall time (accurate on the synchronous
CPU backend and for the chunk-synced decode loop); the bench entry
points (bench.py, tools/*_profile.py) re-``analyze`` with their
properly synced timings, which overwrite the gauges and are what lands
in BENCH_*.json.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax

from . import stats as _stats

__all__ = [
    "PEAKS", "CPU_PEAK", "device_peaks", "program_cost",
    "record_program", "analyze", "observe_wall", "report", "reset",
    "RooflineResult", "AotProgram", "format_report",
]

#: device_kind substring -> (peak bf16 FLOP/s, peak HBM bytes/s).
#: Same provenance as bench.py's PEAK_BF16/HBM_BW tables (public TPU
#: spec sheets); first substring match wins.
PEAKS = {
    "v5 lite": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6": (918e12, 1640e9),
    "v3": (123e12, 900e9),
}

#: CPU fallback so roofline math stays exercised in CI: a rough
#: single-socket figure (order-of-magnitude only — override via env for
#: anything quantitative on CPU).
CPU_PEAK = (200e9, 50e9)

#: env overrides (floats, FLOP/s and bytes/s) — let a deployment pin
#: the exact part's numbers without a code change
ENV_PEAK_FLOPS = "PADDLE_TPU_PEAK_FLOPS"
ENV_PEAK_HBM_BW = "PADDLE_TPU_PEAK_HBM_BW"

#: per-program cost/rate table: name -> {"flops", "bytes", "wall_s",
#: "achieved_flops_per_s", "achieved_bytes_per_s", "mfu", "bw_util"}
_PROGRAMS: Dict[str, dict] = {}


_DEFAULT_DEVICE = None


def device_peaks(device=None):
    """(peak FLOP/s, peak HBM bytes/s) for the device, resolved as:
    env override > device_kind table match > CPU fallback > v5e."""
    env_f = os.environ.get(ENV_PEAK_FLOPS)
    env_b = os.environ.get(ENV_PEAK_HBM_BW)
    if env_f and env_b:
        return float(env_f), float(env_b)
    if device is None:
        global _DEFAULT_DEVICE
        if _DEFAULT_DEVICE is None:
            try:
                _DEFAULT_DEVICE = jax.devices()[0]
            except Exception:
                pass
        device = _DEFAULT_DEVICE
    kind = getattr(device, "device_kind", "").lower()
    platform = getattr(device, "platform", "").lower()
    peak = None
    for k, v in PEAKS.items():
        if k in kind:
            peak = v
            break
    if peak is None:
        peak = CPU_PEAK if platform == "cpu" or kind == "cpu" \
            else PEAKS["v5e"]
    flops, bw = peak
    if env_f:
        flops = float(env_f)
    if env_b:
        bw = float(env_b)
    return flops, bw


def program_cost(compiled) -> Optional[dict]:
    """{"flops", "bytes"} from an executable's XLA cost analysis, or
    None when the backend exposes none. Handles both the list-of-dicts
    (one per computation) and plain-dict shapes ``cost_analysis()``
    returns across jax versions."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if ca is None:
        return None
    if isinstance(ca, (list, tuple)):
        if not ca:
            return None
        flops = sum(float(d.get("flops", 0.0)) for d in ca)
        nbytes = sum(float(d.get("bytes accessed", 0.0)) for d in ca)
    else:
        flops = float(ca.get("flops", 0.0))
        nbytes = float(ca.get("bytes accessed", 0.0))
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    return {"flops": flops, "bytes": nbytes}


def record_program(name: str, compiled=None, *, flops=None,
                   bytes_accessed=None) -> Optional[dict]:
    """Register a compiled program's cost-model numbers. Either pass
    the executable (cost read via ``cost_analysis()``) or explicit
    flops/bytes. Publishes ``compile.flops`` / ``compile.bytes`` gauges
    (most recent program) and keeps the per-program table for
    ``analyze``/``report``."""
    cost = None
    if compiled is not None:
        cost = program_cost(compiled)
    elif flops is not None or bytes_accessed is not None:
        cost = {"flops": float(flops or 0.0),
                "bytes": float(bytes_accessed or 0.0)}
    if cost is None:
        return None
    entry = _PROGRAMS.setdefault(name, {})
    entry.update(cost)
    _stats.set_gauge("compile.flops", cost["flops"])
    _stats.set_gauge("compile.bytes", cost["bytes"])
    _stats.inc("compile.programs_analyzed")
    return dict(cost)


class RooflineResult:
    """Achieved rates for one program against the device peaks."""

    __slots__ = ("name", "flops", "bytes", "wall_s",
                 "achieved_flops_per_s", "achieved_bytes_per_s",
                 "mfu", "bw_util", "peak_flops", "peak_bw")

    def __init__(self, name, flops, nbytes, wall_s, peak_flops, peak_bw):
        self.name = name
        self.flops = flops
        self.bytes = nbytes
        self.wall_s = wall_s
        self.peak_flops = peak_flops
        self.peak_bw = peak_bw
        self.achieved_flops_per_s = flops / wall_s if wall_s > 0 else 0.0
        self.achieved_bytes_per_s = nbytes / wall_s if wall_s > 0 else 0.0
        self.mfu = (self.achieved_flops_per_s / peak_flops
                    if peak_flops else 0.0)
        self.bw_util = (self.achieved_bytes_per_s / peak_bw
                        if peak_bw else 0.0)

    def as_dict(self) -> dict:
        return {
            "flops": self.flops,
            "bytes": self.bytes,
            "wall_s": round(self.wall_s, 6),
            "achieved_flops_per_s": round(self.achieved_flops_per_s, 1),
            "achieved_bytes_per_s": round(self.achieved_bytes_per_s, 1),
            "mfu": round(self.mfu, 4),
            "bw_util": round(self.bw_util, 4),
        }

    def format(self) -> str:
        return (f"roofline[{self.name}]: "
                f"{self.achieved_flops_per_s / 1e9:.1f} GFLOP/s "
                f"(MFU {100 * self.mfu:.1f}%) | "
                f"{self.achieved_bytes_per_s / 1e9:.1f} GB/s "
                f"({100 * self.bw_util:.1f}% of HBM roofline) | "
                f"cost: {self.flops:.3g} flops, {self.bytes:.3g} bytes "
                f"@ {self.wall_s * 1e3:.3f} ms")


def analyze(name: str, wall_s: float, *, calls: int = 1,
            device=None) -> Optional[RooflineResult]:
    """Turn a measured wall time for ``calls`` executions of a recorded
    program into achieved rates; publishes the ``roofline.*`` gauges
    (achieved_flops_per_s, achieved_bytes_per_s, mfu, bw_util for the
    most recently analyzed program) and updates the per-program table.
    Returns None when the program was never recorded or timing is
    degenerate."""
    entry = _PROGRAMS.get(name)
    if not entry or wall_s <= 0 or "flops" not in entry:
        return None
    per_call = wall_s / max(calls, 1)
    peak_flops, peak_bw = device_peaks(device)
    res = RooflineResult(name, entry["flops"], entry["bytes"],
                         per_call, peak_flops, peak_bw)
    entry.update(res.as_dict())
    _stats.set_gauge("roofline.achieved_flops_per_s",
                     res.achieved_flops_per_s)
    _stats.set_gauge("roofline.achieved_bytes_per_s",
                     res.achieved_bytes_per_s)
    _stats.set_gauge("roofline.mfu", res.mfu)
    _stats.set_gauge("roofline.bw_util", res.bw_util)
    return res


def observe_wall(name: str, wall_s: float, *, calls: int = 1) -> None:
    """Cheap per-call hook for the jit layers: record the dispatch wall
    time into a histogram and refresh the roofline gauges. On an async
    backend this measures dispatch, not execution — bench entry points
    re-``analyze`` with synced timings (see module docstring)."""
    if not _stats.is_enabled():
        return
    _stats.observe("roofline.wall_us", wall_s * 1e6 / max(calls, 1))
    analyze(name, wall_s, calls=calls)


def report() -> dict:
    """JSON-able copy of the per-program roofline table (programs with
    recorded cost; rates present once a wall time was analyzed)."""
    return {name: dict(entry) for name, entry in _PROGRAMS.items()}


def format_report() -> str:
    """One printable line per analyzed program (used by
    ``Profiler.summary()`` and the profile tools)."""
    lines = []
    for name, e in _PROGRAMS.items():
        if "mfu" in e:
            lines.append(
                f"roofline[{name}]: "
                f"{e['achieved_flops_per_s'] / 1e9:.1f} GFLOP/s "
                f"(MFU {100 * e['mfu']:.1f}%) | "
                f"{e['achieved_bytes_per_s'] / 1e9:.1f} GB/s "
                f"({100 * e['bw_util']:.1f}% of HBM roofline)")
        else:
            lines.append(f"roofline[{name}]: cost {e['flops']:.3g} flops"
                         f" / {e['bytes']:.3g} bytes (no timing yet)")
    return "\n".join(lines)


def reset() -> None:
    _PROGRAMS.clear()


def _aot_signature(args):
    """Hashable structure+aval key: pytree structure plus each leaf's
    (shape, dtype). Values of traced scalar leaves (python floats/ints,
    e.g. a learning-rate schedule) do NOT enter the key — they are
    traced operands, so the compiled program is value-independent."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return treedef, tuple(
        (tuple(getattr(leaf, "shape", ())),
         str(getattr(leaf, "dtype", type(leaf).__name__)),
         bool(getattr(leaf, "weak_type", not hasattr(leaf, "dtype"))))
        for leaf in leaves)


class AotProgram:
    """Explicit-AOT wrapper over a ``jax.jit`` function.

    First call per input signature does ``jitted.lower(*args).compile()``
    — the same single compilation jit would do, but through the AOT API
    so the executable (and its XLA cost model) is OURS to read — records
    the cost via ``record_program``, and dispatches the compiled object
    directly from then on. Any AOT failure (unsupported arg structure,
    signature drift, backend quirk) permanently falls back to the plain
    jitted call path for that signature, so behavior never regresses.

    Only wrap jitted functions whose every argument is traced (no
    ``static_argnums`` whose VALUES vary — the signature above is
    value-blind).
    """

    __slots__ = ("name", "_jitted", "_exes", "_failed")

    def __init__(self, name: str, jitted):
        self.name = name
        self._jitted = jitted
        self._exes: dict = {}
        self._failed: set = set()

    def __call__(self, *args):
        try:
            sig = _aot_signature(args)
        except Exception:
            return self._jitted(*args)
        exe = self._exes.get(sig)
        if exe is None and sig not in self._failed:
            try:
                exe = self._jitted.lower(*args).compile()
                record_program(self.name, exe)
                self._exes[sig] = exe
            except Exception:
                # genuine trace errors re-raise below through the
                # jitted path, with its own diagnostics intact
                self._failed.add(sig)
                exe = None
        if exe is not None:
            try:
                t0 = time.perf_counter()
                out = exe(*args)
                observe_wall(self.name, time.perf_counter() - t0)
                return out
            except Exception:
                self._exes.pop(sig, None)
                self._failed.add(sig)
        return self._jitted(*args)

    @property
    def jitted(self):
        """The underlying jit function (``lower_hlo``-style callers)."""
        return self._jitted
