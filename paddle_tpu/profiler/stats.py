"""Process-wide runtime metrics registry: counters, gauges, histograms.

TPU-native equivalent of the reference's per-op statistic tables
(reference: python/paddle/profiler/profiler_statistic.py aggregating the
host tracer's RecordEvent stream, plus the op-count tables the C++
HostTraceLevel machinery feeds). Where the reference derives counts from
the trace, this registry is written DIRECTLY by the hot layers — eager
dispatch (per-op call counts, VJP-cache hit/miss), the autograd engine
(sweeps, nodes), jit compile caches (tracings vs hits), the inference
engine (pool pages, decode steps) and the collectives (op counts,
bytes) — so telemetry exists even when no profiler window is open.

Design constraints:

- near-zero cost when disabled: every mutation checks one module-level
  bool before touching the metric (`disable()` turns the whole registry
  into no-ops);
- thread-safe: each metric guards its state with one lock (metrics are
  updated from dispatch on any thread; snapshot() sees consistent
  values);
- JSON-able: ``snapshot()`` returns plain dicts so bench entry points
  (bench.py, tools/op_bench.py) can embed telemetry into BENCH_*.json,
  and the profiler can emit chrome-trace counter events ("ph": "C")
  from the same source.

Conventions for the built-in instrumentation (all optional reading):

- ``op.<name>``                per-op eager dispatch call counters
- ``vjp_cache.{hit,miss,admit,blocklisted,uncacheable}``  taped-VJP
  trace cache outcomes (ops/dispatch.py)
- ``fwd_cache.{hit,miss,admit,blocklisted,blocked,uncacheable}``
  compiled-forward no-grad fast-path outcomes (ops/dispatch.py)
- ``compile.{vjp_trace_us,vjp_build_us}``   histograms of uncached
  jax.vjp trace time / cache-entry build time
- ``compile.fwd_trace_us``     histogram of compiled-forward admission
  trace+compile time
- ``jit.{trace,cache_hit}``    to_static program-cache outcomes
- ``autograd.{sweeps,nodes}``  run_backward sweeps and executed nodes
- ``inference.*`` / ``serving.*``  pool sizes, decode steps, admission
  (``serving.admission_skips`` skip-ahead pass-overs,
  ``serving.prefix_{hit,miss,pages_saved}`` prefix/KV reuse,
  ``serving.wasted_decode_tokens`` chunk tail work past req.done)
- ``serve.*``                  per-request SLO telemetry of the serving
  frontend (paddle_tpu/serving): ``serve.{ttft_ms,tpot_ms,
  request_tpot_ms,queue_wait_ms}`` histograms plus
  ``serve.{submitted,prefill_chunks,prefill_tokens}`` counters
  (``serving.unserved`` stamps requests still waiting when run()
  exits — the ones queue-wait histograms never saw)
- ``journal.{events,dropped}`` serving flight-recorder ring gauges
  (serving/journal.py: events ever recorded / overwritten by wrap)
- ``slo.*``                    SLO monitor (serving/slo.py):
  ``slo.goodput`` rolling fraction of finished requests meeting both
  TTFT and TPOT targets, ``slo.burn_rate`` error-budget burn,
  ``slo.{finished,ok,ttft_miss,tpot_miss}`` counters and
  ``slo.{queue_depth,slot_occupancy}`` load gauges
- ``spec.*``                   speculative decoding
  (inference/speculative.py): ``spec.k`` / ``spec.draft_params``
  gauges and ``spec.{propose_ms,verify_ms}`` timing histograms; the
  round/token accounting lives in
  ``serving.spec_{rounds,drafted_tokens,accepted_tokens,
  rejected_tokens}`` and the ``serve.accept_len`` histogram
- ``quant.{act_quant_calls,a8w8_matmuls}``  executed dynamic
  activation-quant ops / int8 x int8 serving matmuls (A8W8 decode,
  QuantedLinear(a8w8=True)) — counted at the dispatch layer, since
  inside a traced program the quant body runs once per compile
- ``moe.dropped_tokens``       token->expert assignments discarded by
  the MoE capacity bound (incubate/moe/moe_layer.py _gshard_dispatch)
  — counted on the eager forward path only (data-dependent)
- ``lint.{findings,waived}``   tpu_lint results (unwaivered / waived
  finding counts) published by every suite run — the CLI
  (tools/tpu_lint.py) and the bench/profiling preflight gate
  (analysis/preflight.py) — so bench telemetry records the lint state
  its numbers were measured under and bench_gate can ratchet on it
- ``dist.<op>.{calls,bytes}``  collective op counts and payload bytes
- ``fleet.*``                  multi-replica serving router
  (serving/router.py): ``fleet.{replicas,replicas_alive,
  circuit_open}`` gauges and ``fleet.{dispatches,failovers,
  failover_requests,migrations,migrated_pages,hedges,shed}``
  counters — the front-tier health/failover/drain accounting
  tools/serve_top.py --fleet renders — plus the tiered-KV /
  disaggregation accounting: ``fleet.{spills,restores,spill_bytes,
  restore_bytes,host_evictions}`` host-tier page traffic
  (serving/host_tier.py), ``fleet.{handoffs,handoff_pages}``
  prefill→decode slot handoffs, and
  ``fleet.directory_{hits,pulls,misses}`` prefix-directory routing
  verdicts
- ``tier.*``                   host-DRAM KV tier occupancy gauges
  (serving/host_tier.py): ``tier.host_{pages,bytes,
  capacity_bytes}``, summed over every engine's tier in the
  process — the serve_top fleet tier view's source
- ``roofline.*``               achieved FLOP/s / bytes/s / MFU / BW
  utilization vs device peaks (profiler/roofline.py)
- ``hbm.*``                    device memory telemetry
  (profiler/memory.py)
- ``serve.step.*_ms``          per-step serving-time ATTRIBUTION
  (serving/scheduler.py ``_observe_step``): each scheduler step's
  wall time split into ``serve.step.{admit,prefill_chunk,
  decode_chunk,spec_verify,migration,host_overhead,total}_ms``
  histograms on the injectable serving clock — the phase sums equal
  the step wall time (host_overhead is the residual), so "where did
  the step go" is answerable from telemetry alone
- ``telemetry.*``              the continuous time-series sampler's
  own accounting (profiler/timeseries.py):
  ``telemetry.ticks`` sampler passes and ``telemetry.tick_us`` the
  measured per-tick overhead histogram
- ``alert.*``                  the alert rule engine
  (profiler/alerts.py): ``alert.{fired,resolved}`` lifecycle
  counters and the ``alert.active`` gauge
- ``usage.*``                  the per-request usage ledger's own
  accounting (serving/accounting.py): ``usage.records`` closed
  usage records
- ``tenant.*``                 BOUNDED per-tenant rollup gauges
  (serving/accounting.py + serving/slo.py):
  ``tenant.{count,max_share,min_goodput}`` and the index-keyed
  ``tenant.top<i>.device_ms`` top-K slice — never one key per
  tenant; names live in the usage JSONL, not the registry
- ``lora.*``                   batched multi-LoRA serving
  (serving/adapters.py + nn/functional/lora.py):
  ``lora.grouped_launches`` ragged delta-GEMM dispatches (one per
  adaptered chunk; each covers every target projection via the
  traced work map), ``lora.swaps`` hot load/unload events against
  the AdapterBank, and the ``lora.active_adapters`` gauge (loaded,
  non-draining adapter slots)
- ``t.*``                      scratch namespace reserved for tests

Every metric the framework registers MUST use one of these prefixes
(``CONVENTION_PREFIXES``) — tests/test_profiler_stats.py lints the live
registry against it, so fleet aggregation (tools/trace_merge.py) and
the bench gate (tools/bench_gate.py) can rely on stable names.
"""
from __future__ import annotations

import math
import random
import threading
import time
from typing import Dict, Optional

__all__ = [
    "Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
    "inc", "set_gauge", "observe", "snapshot", "reset", "enable",
    "disable", "is_enabled", "timed", "sample_values",
    "CONVENTION_PREFIXES",
]

#: documented metric-name namespaces (see module docstring / README
#: conventions table); the naming lint asserts every registered metric
#: starts with one of these
CONVENTION_PREFIXES = (
    "op.", "vjp_cache.", "fwd_cache.", "compile.", "jit.", "autograd.",
    "inference.", "serving.", "serve.", "journal.", "slo.", "spec.",
    "quant.", "moe.", "dist.", "fleet.", "tier.", "roofline.", "hbm.",
    "lint.", "telemetry.", "alert.", "usage.", "tenant.", "lora.",
    "t.",
)

_ENABLED = True
_REGISTRY_LOCK = threading.Lock()
_COUNTERS: Dict[str, "Counter"] = {}
_GAUGES: Dict[str, "Gauge"] = {}
_HISTOGRAMS: Dict[str, "Histogram"] = {}


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def is_enabled() -> bool:
    return _ENABLED


class Counter:
    """Monotonic event counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-written instantaneous value (pool pages in use, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n=1) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    def dec(self, n=1) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Streaming distribution summary: count/total/min/max, powers-of-2
    buckets, and a bounded RESERVOIR of raw samples.

    The buckets tell a retrace storm (many large observations) from
    steady cache hits and stay exported for chrome-trace counters and
    cross-rank folding (tools/trace_merge.py folds summaries bucket-
    by-bucket). The reservoir fixes their percentile problem: bucket-
    midpoint estimates are off by up to 2x for small-count histograms
    (a 7-request serve bench's p99 TTFT landed on a power-of-2 edge,
    not a real observation). Up to ``RESERVOIR_SIZE`` samples are kept
    verbatim — percentiles are EXACT until the 4097th observation —
    then Vitter's Algorithm R keeps a uniform sample, driven by a
    per-instance seeded RNG so eviction (and thus every snapshot) is
    deterministic for a given observation sequence."""

    __slots__ = ("name", "count", "total", "min", "max", "_buckets",
                 "_samples", "_rng", "_lock")

    #: bucket upper bounds double from 1; observations are expected in
    #: microseconds for the compile/wall-time histograms
    N_BUCKETS = 32
    #: reservoir capacity: exact percentiles up to this many samples,
    #: deterministic uniform sampling beyond (0 disables, falling back
    #: to the bucket estimator)
    RESERVOIR_SIZE = 4096

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._buckets = [0] * self.N_BUCKETS
        self._samples: list = []
        self._rng = random.Random(0x5EED)
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        if not _ENABLED:
            return
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            b = 0
            edge = 1.0
            while v > edge and b < self.N_BUCKETS - 1:
                edge *= 2.0
                b += 1
            self._buckets[b] += 1
            if len(self._samples) < self.RESERVOIR_SIZE:
                self._samples.append(v)
            else:
                # Algorithm R: the i-th observation (count = i+1)
                # replaces a uniformly random reservoir slot with
                # probability RESERVOIR_SIZE / count
                j = self._rng.randrange(self.count)
                if j < self.RESERVOIR_SIZE:
                    self._samples[j] = v

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    @staticmethod
    def _quantile_sorted(s, q: float):
        """Empirical q-quantile of a sorted sample (the ceil(qN)-th
        order statistic — an OBSERVED value, never an interpolation)."""
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        return round(s[idx], 3)

    def _bucket_percentile_locked(self, q: float):
        """Bucket-derived percentile estimate (linear interpolation
        within the winning power-of-2 bucket, clamped to the exact
        min/max) — the pre-reservoir fallback, only reached when
        RESERVOIR_SIZE is 0. Callers hold self._lock."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        for b, n in enumerate(self._buckets):
            if not n:
                continue
            prev, cum = cum, cum + n
            if cum >= target:
                lo = 0.0 if b == 0 else 2.0 ** (b - 1)
                hi = 2.0 ** b
                est = lo + (hi - lo) * (target - prev) / n
                lo_clamp = self.min if self.min is not None else est
                hi_clamp = self.max if self.max is not None else est
                return round(min(max(est, lo_clamp), hi_clamp), 3)
        return self.max

    def _percentile_locked(self, q: float):
        if not self.count:
            return None
        if self._samples:
            return self._quantile_sorted(sorted(self._samples), q)
        return self._bucket_percentile_locked(q)

    def percentile(self, q: float):
        """q-quantile (q in [0, 1]): exact while the reservoir covers
        every observation, reservoir-sampled beyond; None before any
        observation."""
        with self._lock:
            return self._percentile_locked(q)

    def summary(self) -> dict:
        with self._lock:
            # buckets as [upper_edge, count] pairs (nonzero only) so the
            # retrace-storm-vs-steady-hits shape survives into snapshots
            # and can be re-folded across ranks (tools/trace_merge.py)
            buckets = [[(1.0 if b == 0 else 2.0 ** b), n]
                       for b, n in enumerate(self._buckets) if n]
            if self._samples:
                s = sorted(self._samples)
                p50, p90, p99 = (self._quantile_sorted(s, q)
                                 for q in (0.50, 0.90, 0.99))
            else:
                p50, p90, p99 = (self._bucket_percentile_locked(q)
                                 for q in (0.50, 0.90, 0.99))
            return {
                "count": self.count,
                "total": round(self.total, 3),
                "avg": round(self.avg, 3),
                "min": self.min,
                "max": self.max,
                "p50": p50,
                "p90": p90,
                "p99": p99,
                "buckets": buckets,
            }

    def _reset(self) -> None:
        with self._lock:
            self.count = 0
            self.total = 0.0
            self.min = None
            self.max = None
            self._buckets = [0] * self.N_BUCKETS
            self._samples = []
            self._rng = random.Random(0x5EED)


def counter(name: str) -> Counter:
    c = _COUNTERS.get(name)
    if c is None:
        with _REGISTRY_LOCK:
            c = _COUNTERS.setdefault(name, Counter(name))
    return c


def gauge(name: str) -> Gauge:
    g = _GAUGES.get(name)
    if g is None:
        with _REGISTRY_LOCK:
            g = _GAUGES.setdefault(name, Gauge(name))
    return g


def histogram(name: str) -> Histogram:
    h = _HISTOGRAMS.get(name)
    if h is None:
        with _REGISTRY_LOCK:
            h = _HISTOGRAMS.setdefault(name, Histogram(name))
    return h


def inc(name: str, n: int = 1) -> None:
    if _ENABLED:
        counter(name).inc(n)


def set_gauge(name: str, v) -> None:
    if _ENABLED:
        gauge(name).set(v)


def observe(name: str, v) -> None:
    if _ENABLED:
        histogram(name).observe(v)


class timed:
    """Context manager observing its wall time (µs) into a histogram,
    and counting into an optional companion counter::

        with stats.timed("compile.vjp_trace_us"):
            ...  # traced work
    """

    __slots__ = ("_name", "_t0")

    def __init__(self, name: str):
        self._name = name
        self._t0 = None

    def __enter__(self):
        if _ENABLED:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None and _ENABLED:
            observe(self._name,
                    (time.perf_counter_ns() - self._t0) / 1e3)
        return False


def _process_meta() -> dict:
    """Rank stamp for multi-host aggregation: which process produced
    this snapshot (tools/trace_merge.py folds per-rank snapshots into
    one fleet view keyed on this)."""
    pi, pc = 0, 1
    try:
        import jax

        pi, pc = jax.process_index(), jax.process_count()
    except Exception:
        pass
    import os

    return {"process_index": int(pi), "process_count": int(pc),
            "pid": os.getpid()}


def _registered():
    """Consistent copy of the registry's metric lists. Taken under
    ``_REGISTRY_LOCK`` so a snapshot/reset pass racing a writer thread
    that is REGISTERING new names (the time-series sampler hammer
    case) never iterates a mutating dict; per-metric values stay
    guarded by each metric's own lock."""
    with _REGISTRY_LOCK:
        return (sorted(_COUNTERS.items()), sorted(_GAUGES.items()),
                sorted(_HISTOGRAMS.items()))


def snapshot(prefix: Optional[str] = None) -> dict:
    """JSON-able view of every metric (optionally name-prefixed):
    ``{"meta": {...}, "counters": {...}, "gauges": {...},
    "histograms": {...}}`` — ``meta`` stamps the producing rank.
    Safe against concurrent writers/registrations: the name set is
    copied under the registry lock and each histogram summary is read
    under its own lock (no torn count/bucket pairs)."""
    def keep(name):
        return prefix is None or name.startswith(prefix)

    counters, gauges, hists = _registered()
    return {
        "meta": _process_meta(),
        "counters": {n: c.value for n, c in counters
                     if keep(n) and c.value},
        "gauges": {n: g.value for n, g in gauges if keep(n)},
        "histograms": {n: h.summary() for n, h in hists
                       if keep(n) and h.count},
    }


def sample_values(prefix: Optional[str] = None):
    """One lock-cheap telemetry pass (the time-series sampler's tick
    source — profiler/timeseries.py): ``(counters, gauges,
    histograms)`` plain dicts, where histograms carry only the
    ``(count, total)`` pair read under the histogram lock — no
    reservoir sort, no bucket list build, so a tick over hundreds of
    metrics stays microseconds."""
    def keep(name):
        return prefix is None or name.startswith(prefix)

    counters, gauges, hists = _registered()
    hv = {}
    for n, h in hists:
        if not keep(n):
            continue
        with h._lock:
            if h.count:
                hv[n] = (h.count, h.total)
    return ({n: c.value for n, c in counters if keep(n) and c.value},
            {n: g.value for n, g in gauges if keep(n)},
            hv)


def reset() -> None:
    """Zero every metric (keeps the registry's objects alive — cached
    references in hot paths stay valid, and every registered series
    DEFINITION survives: a concurrent sampler keeps reading the same
    metric objects, now zeroed)."""
    counters, gauges, hists = _registered()
    for _, c in counters:
        c._reset()
    for _, g in gauges:
        g._reset()
    for _, h in hists:
        h._reset()


def vjp_cache_hit_rate() -> Optional[float]:
    """hit / (hit + miss) over the taped-VJP trace cache, or None before
    any taped dispatch ran."""
    hit = counter("vjp_cache.hit").value
    miss = counter("vjp_cache.miss").value
    return hit / (hit + miss) if (hit + miss) else None


def fwd_cache_hit_rate() -> Optional[float]:
    """hit / (hit + miss) over the compiled-forward no-grad cache, or
    None before any no-grad dispatch ran with the cache enabled."""
    hit = counter("fwd_cache.hit").value
    miss = counter("fwd_cache.miss").value
    return hit / (hit + miss) if (hit + miss) else None
