"""Benchmark step timer / throughput meter.

TPU-native equivalent of the reference's benchmark timer (reference:
python/paddle/profiler/timer.py — ``benchmark()`` with reader-cost /
batch-cost / ips). The TPU twist: a step's device work completes only
when a host value is fetched, so ``step()`` optionally takes the loss
tensor and forces the scalar fetch before timestamping (see bench.py —
naive timers measure dispatch, not compute, on async transports).
"""
from __future__ import annotations

import time
from typing import Optional

__all__ = ["Benchmark", "benchmark"]


class _EventAverager:
    def __init__(self):
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def record(self, v: float):
        self.total += v
        self.count += 1

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0


class Benchmark:
    """(timer.py Benchmark parity): reader cost, batch cost, ips."""

    def __init__(self):
        self.reader = _EventAverager()
        self.batch = _EventAverager()
        self._last = None
        self._reader_t0 = None
        self._samples = 0

    def begin(self):
        self._last = time.perf_counter()
        self.reader.reset()
        self.batch.reset()
        self._samples = 0

    def before_reader(self):
        self._reader_t0 = time.perf_counter()

    def after_reader(self):
        if self._reader_t0 is not None:
            self.reader.record(time.perf_counter() - self._reader_t0)

    def step(self, num_samples: int = 1, sync_value=None):
        """End of one step. ``sync_value``: a Tensor/array whose host
        fetch forces device completion (pass the loss)."""
        if sync_value is not None:
            import numpy as np

            arr = getattr(sync_value, "_data", sync_value)
            np.asarray(arr.ravel()[0] if hasattr(arr, "ravel") else arr)
        now = time.perf_counter()
        if self._last is not None:
            self.batch.record(now - self._last)
        self._last = now
        self._samples += num_samples

    def step_info(self, unit: str = "samples") -> str:
        ips = (1.0 / self.batch.avg) if self.batch.avg else 0.0
        return (f"reader_cost: {self.reader.avg:.5f} s "
                f"batch_cost: {self.batch.avg:.5f} s "
                f"ips: {ips * (self._samples / max(self.batch.count, 1)):.2f}"
                f" {unit}/s")

    @property
    def ips(self) -> float:
        if not self.batch.avg or not self.batch.count:
            return 0.0
        per_step = self._samples / self.batch.count
        return per_step / self.batch.avg


_bench: Optional[Benchmark] = None


def benchmark() -> Benchmark:
    global _bench
    if _bench is None:
        _bench = Benchmark()
    return _bench
