"""Continuous telemetry: bounded time-series sampling over the stats
registry, with Prometheus/JSONL exporters and fleet aggregation.

Every signal the stack publishes so far is a point-in-time
``stats.snapshot()`` or an end-of-run bench block — "goodput dipped
for 30 s during a failover" is invisible by construction. This module
closes that gap with a :class:`TimeSeriesSampler`: a periodic
(background thread, or explicit ``tick()`` for deterministic tests)
pass that folds the registry into per-metric bounded ring windows:

- **counters** record ``(ts, cumulative, rate)`` — the delta rate
  (events/s between ticks: tokens/s, faults/s) is derived at sample
  time, so the ring answers "how fast NOW" without post-processing;
- **gauges** record ``(ts, value)`` — instantaneous levels (queue
  depth, goodput, burn rate, HBM bytes);
- **histograms** record ``(ts, count, total)`` — the cheap pair read
  under the histogram lock (no reservoir sort per tick), from which
  per-interval event rates and means derive.

Design constraints (the PR 1 registry / PR 9 journal discipline):

- **bounded**: each metric's ring holds ``window`` points
  (``FLAGS_telemetry_window``) — fixed memory however long the serve
  runs;
- **lock-cheap**: one pass per tick through
  ``stats.sample_values()`` (registry lock for the name copy,
  per-histogram lock for the count/total pair only);
- **zero cost when disabled**: a disabled sampler allocates NO rings
  and ``tick()`` is a single attribute test;
- **clock-seam timestamps**: tick timestamps route through the
  serving clock (serving/faults.py) when available, so ManualClock
  tests get exact, deterministic delta rates.

Exporters:

- ``dump_jsonl`` — append-only JSONL, one tick per line
  (``{"ts": ..., "counters": {n: [cum, rate]}, "gauges": {...},
  "histograms": {n: [count, total]}, "alerts": [...]}``), loadable
  offline by ``load_jsonl`` / ``tools/serve_top.py --history`` and
  foldable across ranks by ``tools/trace_merge.py``;
- ``prometheus_text`` / ``start_http_server`` — text-format scrape
  (stdlib ``http.server`` thread, ``FLAGS_telemetry_port``) with
  conventional naming: counters ``*_total`` (monotone), histograms
  cumulative ``*_bucket{le=...}`` + ``*_sum``/``*_count``;
- ``aggregate_ticks`` — fold per-replica/per-rank series into one
  fleet-level set with the trace_merge fold semantics (counters SUM,
  gauges MAX, histogram counts/totals SUM); ``FleetRouter.
  start_telemetry`` serves that fold on one port.

This module is deliberately stdlib-only at import time (the flags /
stats imports are lazy and fall back) so ``tools/trace_merge.py`` and
``tools/serve_top.py`` can load it standalone for offline folds.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = [
    "TimeSeriesSampler", "registry_source", "engine_source",
    "aggregate_ticks", "load_jsonl", "prometheus_text",
    "tick_prometheus_text", "start_http_server", "TelemetryServer",
]

#: fallback defaults when core.flags is unavailable (standalone load)
_DEFAULT_INTERVAL_MS = 0.0
_DEFAULT_WINDOW = 512


def _flag(name, default):
    try:
        from ..core.flags import flag

        return flag(name)
    except Exception:
        return default


def _clock():
    """The serving clock seam when importable (serving/faults.py),
    else a real-monotonic stand-in with the same now()/sleep() API."""
    try:
        from ..serving import faults as _faults

        return _faults.clock()
    except Exception:
        class _Wall:
            def now(self):
                return time.monotonic()

            def sleep(self, s):
                if s > 0:
                    time.sleep(s)

        return _Wall()


def registry_source() -> Callable[[], tuple]:
    """The default tick source: one ``stats.sample_values()`` pass
    over the process-wide registry."""
    from . import stats as _stats

    return _stats.sample_values


def engine_source(eng) -> Callable[[], tuple]:
    """A PER-REPLICA tick source reading one ServingEngine's live
    state directly (the process registry is shared by every replica,
    so per-replica series must come from the engine objects): request
    completions as a counter, queue/occupancy/SLO levels as gauges.
    Counter names are chosen so the fleet fold's SUM is exact
    (completions add across replicas; goodput/occupancy MAX)."""
    def src():
        counters = {"serve.finished": len(eng.finished)}
        jr = getattr(eng, "journal", None)
        if jr is not None:
            counters["journal.events"] = jr.recorded
        mon = getattr(eng, "slo_monitor", None)
        gauges = {
            "slo.queue_depth": eng.queue_depth,
            "slo.slot_occupancy": (eng.num_active / eng.max_batch
                                   if eng.max_batch else 0.0),
        }
        if mon is not None and mon.goodput is not None:
            gauges["slo.goodput"] = mon.goodput
            gauges["slo.burn_rate"] = mon.burn_rate
            tg = mon.tenant_min_goodput
            if tg is not None:
                gauges["tenant.min_goodput"] = tg
        u = getattr(eng, "usage", None)
        if u is not None:
            # bounded tenant slice (ISSUE 17): count + hog share +
            # index-keyed top-K device time — never a key per tenant
            from ..core.flags import flag as _flag

            gauges["tenant.count"] = u.tenant_count()
            gauges["tenant.max_share"] = round(u.max_share(), 4)
            for i, (_, ns) in enumerate(
                    u.top_tenants(int(_flag("usage_top_k")))):
                gauges[f"tenant.top{i}.device_ms"] = \
                    round(ns / 1e6, 3)
        return counters, gauges, {}
    return src


class TimeSeriesSampler:
    """Periodic sampler folding a metrics source into bounded rings.

    Usage (deterministic test form)::

        clk = ManualClock()
        s = TimeSeriesSampler(interval_ms=100, window=64, clock=clk)
        s.tick(); clk.advance(2.0); s.tick()
        s.rate("serving.decode_steps")   # exact delta rate
        s.aggregate("slo.goodput")       # {min, mean, max, p99, last}

    Background form (real serves): ``start()`` spawns a daemon thread
    ticking every ``interval_ms``; ``stop()`` joins it. Timestamps
    route through the serving clock seam either way. A sampler built
    disabled (``enabled=False``, or default-constructed while
    ``FLAGS_telemetry_interval_ms`` is 0) allocates no rings and every
    ``tick()`` is one attribute test.
    """

    def __init__(self, interval_ms: Optional[float] = None,
                 window: Optional[int] = None, clock=None,
                 source: Optional[Callable[[], tuple]] = None,
                 enabled: Optional[bool] = None):
        if interval_ms is None:
            interval_ms = float(_flag("telemetry_interval_ms",
                                      _DEFAULT_INTERVAL_MS))
        if window is None:
            window = int(_flag("telemetry_window", _DEFAULT_WINDOW))
        self.interval_ms = float(interval_ms)
        self.window = max(int(window), 2)
        self.enabled = (self.interval_ms > 0) if enabled is None \
            else bool(enabled)
        self._clock = clock if clock is not None else _clock()
        self._source = source if source is not None \
            else registry_source()
        self._alerts = None
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.n_ticks = 0
        self._dumped = 0
        if self.enabled:
            #: metric name -> deque of points (see module docstring)
            self._counters: Dict[str, deque] = {}
            self._gauges: Dict[str, deque] = {}
            self._hists: Dict[str, deque] = {}
            self._ticks: deque = deque(maxlen=self.window)
            self._last_cum: Dict[str, tuple] = {}
        else:
            # zero-cost discipline: nothing allocated, nothing to leak
            self._counters = self._gauges = self._hists = None
            self._ticks = None
            self._last_cum = None

    # ---------------- sampling ----------------

    def attach_alerts(self, engine) -> "TimeSeriesSampler":
        """Evaluate an :class:`profiler.alerts.AlertEngine` every tick;
        the tick record then carries the active alert names (rendered
        by serve_top --history)."""
        self._alerts = engine
        return self

    def tick(self) -> Optional[dict]:
        """One sampling pass: read the source, derive counter delta
        rates against the previous tick, append one point per metric,
        evaluate attached alert rules. Returns the tick record (the
        JSONL line shape) or None when disabled."""
        if not self.enabled:
            return None
        t_wall = time.perf_counter_ns()
        with self._lock:
            ts = self._clock.now()
            counters, gauges, hists = self._source()
            rec_c = {}
            for n, cum in counters.items():
                prev = self._last_cum.get(n)
                rate = None
                if prev is not None:
                    dt = ts - prev[0]
                    if dt > 0:
                        rate = (cum - prev[1]) / dt
                self._last_cum[n] = (ts, cum)
                self._ring(self._counters, n).append((ts, cum, rate))
                rec_c[n] = [cum, rate]
            for n, v in gauges.items():
                self._ring(self._gauges, n).append((ts, v))
            rec_h = {}
            for n, (count, total) in hists.items():
                self._ring(self._hists, n).append((ts, count, total))
                rec_h[n] = [count, round(total, 6)]
            rec = {"ts": round(ts, 6), "counters": rec_c,
                   "gauges": gauges, "histograms": rec_h}
            self.n_ticks += 1
        if self._alerts is not None:
            self._alerts.evaluate(self)
            rec["alerts"] = sorted(self._alerts.active)
        with self._lock:
            self._ticks.append(rec)
        try:  # the sampler's own accounting (skipped standalone)
            from . import stats as _stats

            _stats.inc("telemetry.ticks")
            _stats.observe("telemetry.tick_us",
                           (time.perf_counter_ns() - t_wall) / 1e3)
        except Exception:
            pass
        return rec

    def _ring(self, table, name):
        ring = table.get(name)
        if ring is None:
            ring = table[name] = deque(maxlen=self.window)
        return ring

    # ---------------- reading ----------------

    def series(self, name: str) -> List[tuple]:
        """The raw ring for one metric: counter points are
        ``(ts, cumulative, rate)``, gauge points ``(ts, value)``,
        histogram points ``(ts, count, total)``."""
        if not self.enabled:
            return []
        with self._lock:
            for table in (self._counters, self._gauges, self._hists):
                if name in table:
                    return list(table[name])
        return []

    def metrics(self) -> List[str]:
        if not self.enabled:
            return []
        with self._lock:
            return sorted(set(self._counters) | set(self._gauges)
                          | set(self._hists))

    def value(self, name: str):
        """Latest level: gauge value, counter delta rate, or histogram
        count — the alert engine's per-tick read."""
        if not self.enabled:
            return None
        with self._lock:
            if name in self._gauges and self._gauges[name]:
                return self._gauges[name][-1][1]
            if name in self._counters and self._counters[name]:
                return self._counters[name][-1][2]
            if name in self._hists and self._hists[name]:
                return self._hists[name][-1][1]
        return None

    def rate(self, name: str):
        """Latest counter delta rate (events/s between the last two
        ticks), None before two ticks saw the counter."""
        pts = self.series(name)
        return pts[-1][2] if pts and len(pts[-1]) == 3 else None

    def rates(self, name: str) -> List[float]:
        """Every non-None delta rate in the window (spike rules read
        the trailing distribution)."""
        return [p[2] for p in self.series(name)
                if len(p) == 3 and p[2] is not None]

    def cum(self, name: str):
        """Latest cumulative counter value."""
        pts = self.series(name)
        return pts[-1][1] if pts else None

    def aggregate(self, name: str) -> Optional[dict]:
        """Window aggregates over the metric's ring — gauges aggregate
        their values, counters their delta rates."""
        pts = self.series(name)
        if not pts:
            return None
        if len(pts[0]) == 3 and name in (self._counters or {}):
            vals = [p[2] for p in pts if p[2] is not None]
        else:
            vals = [p[1] for p in pts]
        if not vals:
            return None
        s = sorted(vals)
        p99 = s[min(len(s) - 1, max(0, -(-99 * len(s) // 100) - 1))]
        return {"n": len(vals), "min": s[0], "max": s[-1],
                "mean": sum(vals) / len(vals), "p99": p99,
                "last": vals[-1]}

    def ticks(self) -> List[dict]:
        """The retained tick records, oldest first (the JSONL dump /
        serve_top --history live input)."""
        if not self.enabled:
            return []
        with self._lock:
            return list(self._ticks)

    # ---------------- background thread ----------------

    def start(self) -> "TimeSeriesSampler":
        """Spawn the background sampling thread (daemon). The pace is
        wall time (interruptible wait); every timestamp still routes
        through the clock seam. No-op when disabled or started."""
        if not self.enabled or self.interval_ms <= 0:
            return self
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop_evt.clear()

        def loop():
            dt = self.interval_ms / 1e3
            while not self._stop_evt.wait(dt):
                self.tick()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="telemetry-sampler")
        self._thread.start()
        return self

    def stop(self, final_tick: bool = True) -> None:
        """Stop the background thread; by default take one last tick
        so the series ends at the run's end state."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_tick:
            self.tick()

    # ---------------- exporters ----------------

    def dump_jsonl(self, path: str) -> str:
        """APPEND the ticks not yet dumped as JSONL lines (one tick
        per line) — repeated calls grow the file monotonically, so a
        long serve can checkpoint its series without rewriting."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        ticks = self.ticks()
        new = ticks[self._dumped:] if self._dumped <= len(ticks) \
            else ticks
        with open(path, "a") as f:
            for rec in new:
                f.write(json.dumps(rec) + "\n")
        self._dumped = len(ticks)
        return path

    def prometheus_text(self) -> str:
        """Text-format scrape of this sampler's LATEST tick."""
        ticks = self.ticks()
        return tick_prometheus_text(ticks[-1]) if ticks else ""


def load_jsonl(path: str) -> List[dict]:
    """Parse a series dump back into tick records (offline input for
    serve_top --history and the trace_merge series fold)."""
    ticks = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                ticks.append(json.loads(line))
    ticks.sort(key=lambda t: t.get("ts", 0.0))
    return ticks


# ---------------------------------------------------------------------
# fleet fold
# ---------------------------------------------------------------------

def aggregate_ticks(per_rank: List[List[dict]]) -> List[dict]:
    """Fold per-replica/per-rank tick series into ONE fleet-level
    series, with the trace_merge fold semantics: ticks align by
    timestamp order (each rank's series is sorted by ts, then tick i
    folds with tick i of every other rank — samplers on one cadence
    line up exactly), counters SUM (cumulative and rate), gauges MAX,
    histogram counts/totals SUM, alert sets union. The folded tick's
    ``ts`` is the max of its members' (the fleet saw the state by
    then)."""
    ranks = [sorted(t, key=lambda d: d.get("ts", 0.0))
             for t in per_rank if t]
    if not ranks:
        return []
    out = []
    for i in range(max(len(r) for r in ranks)):
        members = [r[i] for r in ranks if i < len(r)]
        counters: dict = {}
        gauges: dict = {}
        hists: dict = {}
        alerts: set = set()
        for m in members:
            for n, (cum, rate) in m.get("counters", {}).items():
                c = counters.setdefault(n, [0, None])
                c[0] += cum
                if rate is not None:
                    c[1] = rate if c[1] is None else c[1] + rate
            for n, v in m.get("gauges", {}).items():
                gauges[n] = v if n not in gauges \
                    else max(gauges[n], v)
            for n, (count, total) in m.get("histograms", {}).items():
                h = hists.setdefault(n, [0, 0.0])
                h[0] += count
                h[1] += total
            alerts.update(m.get("alerts", []))
        rec = {"ts": max(m.get("ts", 0.0) for m in members),
               "counters": counters, "gauges": gauges,
               "histograms": hists}
        if alerts:
            rec["alerts"] = sorted(alerts)
        out.append(rec)
    return out


# ---------------------------------------------------------------------
# Prometheus text-format exporter
# ---------------------------------------------------------------------

def _prom_name(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a ``stats.snapshot()``-shaped dict (default: a fresh
    snapshot of the process registry) in Prometheus text format:
    counters as monotone ``<name>_total``, gauges plain, histograms
    as CUMULATIVE ``<name>_bucket{le="..."}`` rows plus
    ``_sum``/``_count`` (the power-of-2 registry buckets become the
    ``le`` edges; the implicit ``+Inf`` bucket closes the series)."""
    if snap is None:
        from . import stats as _stats

        snap = _stats.snapshot()
    lines: List[str] = []
    for n, v in sorted(snap.get("counters", {}).items()):
        pn = _prom_name(n) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {v}")
    for n, v in sorted(snap.get("gauges", {}).items()):
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    for n, h in sorted(snap.get("histograms", {}).items()):
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for edge, cnt in h.get("buckets", []):
            cum += cnt
            lines.append(f'{pn}_bucket{{le="{edge:g}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h.get("count", 0)}')
        lines.append(f"{pn}_sum {h.get('total', 0.0)}")
        lines.append(f"{pn}_count {h.get('count', 0)}")
    return "\n".join(lines) + "\n"


def tick_prometheus_text(tick: dict) -> str:
    """Prometheus rendering of one (possibly fleet-folded) tick
    record — counters monotone ``*_total``, gauges plain, histogram
    pairs as ``_sum``/``_count`` (per-bucket shape lives in the full
    registry exporter, not the light tick pair)."""
    lines: List[str] = []
    for n, (cum, _rate) in sorted(tick.get("counters", {}).items()):
        pn = _prom_name(n) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {cum}")
    for n, v in sorted(tick.get("gauges", {}).items()):
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    for n, (count, total) in sorted(
            tick.get("histograms", {}).items()):
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} histogram")
        lines.append(f'{pn}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{pn}_sum {total}")
        lines.append(f"{pn}_count {count}")
    return "\n".join(lines) + "\n"


class TelemetryServer:
    """Stdlib HTTP scrape endpoint: a daemon ``ThreadingHTTPServer``
    answering every GET with ``render()`` as
    ``text/plain; version=0.0.4`` (the Prometheus exposition type).
    ``port=0`` binds an ephemeral port (tests); ``.port`` reports the
    bound one."""

    def __init__(self, port: int,
                 render: Optional[Callable[[], str]] = None,
                 host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler
        from http.server import ThreadingHTTPServer

        render = render if render is not None else prometheus_text

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                try:
                    body = render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # a failed render must not
                    # kill the serve thread
                    try:
                        self.send_error(500, str(e)[:100])
                    except Exception:
                        pass

            def log_message(self, *a):  # silence per-scrape stderr
                pass

        self._srv = ThreadingHTTPServer((host, int(port)), _Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name=f"telemetry-http-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5.0)


def start_http_server(port: Optional[int] = None,
                      render: Optional[Callable[[], str]] = None
                      ) -> Optional[TelemetryServer]:
    """Start the scrape endpoint on ``port`` (default
    ``FLAGS_telemetry_port``; None is returned when that is 0 — the
    no-exporter default). ``render`` defaults to the full-registry
    Prometheus text; FleetRouter passes its fleet-fold renderer."""
    if port is None:
        port = int(_flag("telemetry_port", 0))
        if port <= 0:
            return None
    return TelemetryServer(int(port), render)
