"""paddle_tpu.quantization — PTQ/QAT framework.

TPU-native equivalent of the reference's quantization package (reference:
python/paddle/quantization — QuantConfig config.py, PTQ ptq.py, QAT
qat.py, observers observer.py, fake-quant quanters). The quantized
execution target differs deliberately: instead of emitting int8 GPU
kernels, convert() produces weight-only-int8 Linears whose int8 weights
are dequantized into the matmul — the TPU-idiomatic deployment (HBM
traffic halves; MXU math stays bf16/fp32).
"""
from __future__ import annotations

from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

from .dynamic import dynamic_act_quant, int8_dot_dequant
from .factory import (ClassWithArguments, ObserverFactory, QuanterFactory,
                      instantiate, observer, quanter)

__all__ = [
    "QuantConfig", "SingleLayerConfig", "PTQ", "QAT", "AbsmaxObserver",
    "MovingAverageObserver", "QuantedLinear", "FakeQuant", "quant_dequant",
    "BaseObserver", "BaseQuanter", "QuanterFactory", "ObserverFactory",
    "quanter", "observer", "FakeQuanterWithAbsMaxObserver",
    "post_training_quantize", "dynamic_act_quant", "int8_dot_dequant",
]


def post_training_quantize(model, calib_reader=None, **kw):
    """Quantize a SAVED inference artifact (the serving-team workflow;
    reference static/quantization/post_training_quantization.py). See
    paddle_tpu.static.quantization.post_training_quantize."""
    from ..static.quantization import post_training_quantize as _ptq

    return _ptq(model, calib_reader, **kw)


class BaseObserver:
    """Observer contract (reference: quantization/base_observer.py —
    collect statistics during calibration, expose the deployed scale)."""

    def observe(self, arr) -> None:
        raise NotImplementedError

    def scale(self) -> float:
        raise NotImplementedError

    def cal_thresholds(self) -> None:
        """Finalize statistics (no-op for running-stat observers)."""


class BaseQuanter(BaseObserver):
    """Quanter contract (reference: quantization/base_quanter.py): an
    observer that also simulates quantization in the forward pass."""

    def __call__(self, x):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Per-tensor absmax range observer (reference:
    quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, arr) -> None:
        self._absmax = max(self._absmax,
                           float(jnp.max(jnp.abs(arr))))

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return (self._absmax / qmax) if self._absmax > 0 else 1.0


class MovingAverageObserver(AbsmaxObserver):
    """EMA absmax observer (reference: observers emulating
    moving_average_abs_max)."""

    def __init__(self, quant_bits: int = 8, momentum: float = 0.9):
        super().__init__(quant_bits)
        self.momentum = momentum
        self._seen = False

    def observe(self, arr) -> None:
        cur = float(jnp.max(jnp.abs(arr)))
        if not self._seen:
            self._absmax, self._seen = cur, True
        else:
            self._absmax = (self.momentum * self._absmax
                            + (1 - self.momentum) * cur)


def quant_dequant(arr, scale: float, bits: int = 8):
    """Simulated quantization (round-to-nearest, symmetric)."""
    qmax = 2 ** (bits - 1) - 1
    q = jnp.clip(jnp.round(arr / scale), -qmax - 1, qmax)
    return q * scale


class SingleLayerConfig:
    """Per-layer activation/weight quanter pair (reference:
    quantization/config.py:36 SingleLayerConfig)."""

    def __init__(self, activation=None, weight=None):
        self._activation = activation
        self._weight = weight

    @property
    def activation(self):
        return self._activation

    @property
    def weight(self):
        return self._weight

    def __str__(self):
        return f"activation: {self._activation}\nweight: {self._weight}"


class QuantConfig:
    """Which layers get quantized and with what observers/quanters
    (reference: quantization/config.py QuantConfig — resolution priority
    layer-instance > qualified-name > type > global default; plus
    QAT layer mappings and customized leaves)."""

    def __init__(self, activation=None, weight=None):
        self._default_act = activation or (lambda: MovingAverageObserver())
        self._default_wt = weight or (lambda: AbsmaxObserver())
        self._has_explicit_default = (activation is not None
                                      or weight is not None)
        self._layer_configs: Dict[int, SingleLayerConfig] = {}
        self._name_configs: Dict[str, SingleLayerConfig] = {}
        self._type_configs: Dict[Type, SingleLayerConfig] = {}
        self._qat_layer_mappings: Dict[Type, Type] = {}
        self._customized_leaves: list = []

    # ---- reference API (config.py) ----
    def add_layer_config(self, layer, activation=None, weight=None):
        """Pin a config to specific layer INSTANCES (config.py
        add_layer_config — highest priority)."""
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs[id(l)] = SingleLayerConfig(
                activation or self._default_act,
                weight or self._default_wt)

    def add_name_config(self, name, activation=None, weight=None):
        """Pin a config to qualified sublayer names (config.py
        add_name_config)."""
        names = name if isinstance(name, (list, tuple)) else [name]
        for n in names:
            self._name_configs[n] = SingleLayerConfig(
                activation or self._default_act,
                weight or self._default_wt)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_configs[t] = SingleLayerConfig(
                activation or self._default_act,
                weight or self._default_wt)

    def add_qat_layer_mapping(self, source: Type, target: Type):
        """Register source layer type -> QAT-wrapped type (config.py
        add_qat_layer_mapping; default mapping covers Linear)."""
        self._qat_layer_mappings[source] = target

    def add_customized_leaves(self, layer_type):
        """Types treated as leaves during traversal (config.py
        add_customized_leaves)."""
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        self._customized_leaves.extend(types)

    @property
    def qat_layer_mappings(self):
        from ..nn.layers.common import Linear

        out = {Linear: _QATLinear}
        out.update(self._qat_layer_mappings)
        return out

    def _is_leaf(self, layer: Layer) -> bool:
        return type(layer) in tuple(self._customized_leaves)

    def _get_config_by_layer(self, qualname: str,
                             layer: Layer) -> Optional[SingleLayerConfig]:
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        if qualname in self._name_configs:
            return self._name_configs[qualname]
        for t, cfg in self._type_configs.items():
            if type(layer) is t:
                return cfg
        # global default: applies to mappable types (Linear + registered
        # mappings) when nothing narrower was configured
        explicit = (self._layer_configs or self._name_configs
                    or self._type_configs)
        if type(layer) in self.qat_layer_mappings and (
                self._has_explicit_default or not explicit):
            return SingleLayerConfig(self._default_act, self._default_wt)
        return None

    # back-compat shim (round-2 internal API)
    def _config_for(self, layer: Layer) -> Optional[dict]:
        cfg = self._get_config_by_layer("", layer)
        if cfg is None:
            return None
        return {"activation": cfg.activation, "weight": cfg.weight}

    def __str__(self):
        lines = ["Global config:",
                 str(SingleLayerConfig(self._default_act,
                                       self._default_wt))]
        for n, c in self._name_configs.items():
            lines.append(f"{n}:\n{c}")
        return "\n".join(lines)


def _walk_quantizable(model: Layer, prefix=""):
    """Yield (parent, local_name, qualified_name, child) pre-order."""
    for name, child in list(model.named_children()):
        qual = f"{prefix}.{name}" if prefix else name
        yield model, name, qual, child


class _ObservedLinear(Layer):
    """Calibration wrapper: records input/weight ranges each forward."""

    def __init__(self, inner, act_obs, wt_obs):
        super().__init__()
        self.inner = inner
        self.act_obs = act_obs
        self.wt_obs = wt_obs
        self.wt_obs.observe(inner.weight._data)

    def forward(self, x):
        self.act_obs.observe(x._data if isinstance(x, Tensor) else x)
        return self.inner(x)


class QuantedLinear(Layer):
    """Deployed int8 Linear: int8 weights + fp scale. Two execution
    modes (reference: the int8 path of quantization-converted Linear):

    - weight-only (default): int8 weights dequantized into the matmul —
      HBM traffic halves, MXU math stays float (TPU-idiomatic form);
    - ``a8w8=True``: activations dynamically quantized per token
      (``dynamic_act_quant``) into an int8 x int8 matmul with int32
      accumulation and one accumulator dequant — the deployment shape
      of the reference's fused_multi_transformer_int8 serving matmuls.
    """

    def __init__(self, float_linear, wt_scale: float,
                 act_scale: Optional[float] = None, bits: int = 8,
                 a8w8: bool = False):
        super().__init__()
        w = float_linear.weight._data
        qmax = 2 ** (bits - 1) - 1
        self.w_int = jnp.clip(jnp.round(w / wt_scale), -qmax - 1,
                              qmax).astype(jnp.int8)
        self.wt_scale = wt_scale
        self.act_scale = act_scale
        self.bias = float_linear.bias
        self.bits = bits
        self.a8w8 = bool(a8w8)

    def forward(self, x):
        xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        if self.a8w8:
            from ..profiler import stats as _stats

            from .dynamic import dynamic_act_quant, int8_dot_dequant

            _stats.inc("quant.act_quant_calls")
            _stats.inc("quant.a8w8_matmuls")
            xq, xs = dynamic_act_quant(xd)
            out = int8_dot_dequant(
                xq, xs, self.w_int,
                jnp.asarray(self.wt_scale, jnp.float32),
                bias=None if self.bias is None else self.bias._data,
                out_dtype=xd.dtype)
            return Tensor(out)
        w = self.w_int.astype(xd.dtype) * jnp.asarray(self.wt_scale,
                                                      xd.dtype)
        out = xd @ w
        if self.bias is not None:
            out = out + self.bias._data
        return Tensor(out)


class PTQ:
    """Post-training quantization driver (reference: quantization/ptq.py:
    quantize() instruments, calibration runs observe, convert() deploys)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, prefix="") -> Layer:
        from ..nn.layers.common import Linear

        for parent, name, qual, child in _walk_quantizable(model, prefix):
            cfg = self.config._get_config_by_layer(qual, child)
            if cfg is not None:
                # deployment (QuantedLinear) assumes x @ weight semantics
                if not isinstance(child, Linear):
                    raise NotImplementedError(
                        f"PTQ supports Linear layers; got "
                        f"{type(child).__name__} for {qual!r}")
                parent.add_sublayer(name, _ObservedLinear(
                    child, instantiate(cfg.activation),
                    instantiate(cfg.weight)))
            elif not self.config._is_leaf(child):
                self.quantize(child, qual)
        return model

    def convert(self, model: Layer, a8w8: bool = False) -> Layer:
        """Deploy observed layers as QuantedLinear. ``a8w8=True`` emits
        dynamic-activation int8 x int8 layers instead of weight-only
        (the static ``act_obs`` scale is still recorded for audits)."""
        for name, child in list(model.named_children()):
            if isinstance(child, _ObservedLinear):
                model.add_sublayer(name, QuantedLinear(
                    child.inner, child.wt_obs.scale(),
                    child.act_obs.scale(), a8w8=a8w8))
            else:
                self.convert(child, a8w8=a8w8)
        return model


class FakeQuant(Layer):
    """Straight-through fake-quant node for QAT (reference: quanters/
    fake_quanter.py — quant-dequant forward, identity gradient)."""

    def __init__(self, bits: int = 8, observer=None):
        super().__init__()
        self.bits = bits
        self.observer = observer or MovingAverageObserver(bits)

    def forward(self, x):
        from ..ops.dispatch import eager_apply, as_tensor_args

        (t,) = as_tensor_args(x)
        if self.training:  # eval passes must not shift the statistics
            self.observer.observe(t._data)
        scale = self.observer.scale()

        def raw(arr):
            q = quant_dequant(arr, scale, self.bits)
            # straight-through: gradient flows as identity
            return arr + jax.lax.stop_gradient(q - arr)

        return eager_apply("fake_quant", raw, [t])


@quanter("FakeQuanterWithAbsMaxObserver")
class FakeQuanterWithAbsMaxObserverLayer(MovingAverageObserver,
                                         BaseQuanter):
    """EMA-absmax fake quanter (reference: quanters/abs_max.py —
    FakeQuanterWithAbsMaxObserverLayer; the module-level
    ``FakeQuanterWithAbsMaxObserver`` symbol is the registered factory).
    Usable directly as an observer inside FakeQuant or standalone as a
    quant-dequant callable."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 quant_bits: int = None, **kwargs):
        bits = quant_bits if quant_bits is not None else bit_length
        super().__init__(quant_bits=bits, momentum=moving_rate)

    def __call__(self, x):
        arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        self.observe(arr)
        return Tensor(quant_dequant(arr, self.scale(), self.quant_bits))


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py):
    wraps eligible layers' inputs+weights with FakeQuant nodes."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, prefix="") -> Layer:
        mappings = self.config.qat_layer_mappings
        for parent, name, qual, child in _walk_quantizable(model, prefix):
            cfg = self.config._get_config_by_layer(qual, child)
            if cfg is not None:
                target = mappings.get(type(child))
                if target is None:
                    raise NotImplementedError(
                        f"no QAT layer mapping for "
                        f"{type(child).__name__} ({qual!r}); register "
                        f"one via QuantConfig.add_qat_layer_mapping")
                parent.add_sublayer(name, target(
                    child, instantiate(cfg.activation),
                    instantiate(cfg.weight)))
            elif not self.config._is_leaf(child):
                self.quantize(child, qual)
        return model

    def convert(self, model: Layer) -> Layer:
        for name, child in list(model.named_children()):
            if isinstance(child, _QATLinear):
                model.add_sublayer(name, QuantedLinear(
                    child.inner, child.wt_fq.observer.scale(),
                    child.act_fq.observer.scale()))
            else:
                self.convert(child)
        return model


class _QATLinear(Layer):
    def __init__(self, inner, act_obs=None, wt_obs=None):
        super().__init__()
        self.inner = inner
        self.act_fq = FakeQuant(observer=act_obs or MovingAverageObserver())
        self.wt_fq = FakeQuant(observer=wt_obs or AbsmaxObserver())

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        xq = self.act_fq(x)
        wq = self.wt_fq(self.inner.weight)
        return F.linear(xq, wq, self.inner.bias)
