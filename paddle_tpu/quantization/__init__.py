"""paddle_tpu.quantization — PTQ/QAT framework.

TPU-native equivalent of the reference's quantization package (reference:
python/paddle/quantization — QuantConfig config.py, PTQ ptq.py, QAT
qat.py, observers observer.py, fake-quant quanters). The quantized
execution target differs deliberately: instead of emitting int8 GPU
kernels, convert() produces weight-only-int8 Linears whose int8 weights
are dequantized into the matmul — the TPU-idiomatic deployment (HBM
traffic halves; MXU math stays bf16/fp32).
"""
from __future__ import annotations

from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = [
    "QuantConfig", "PTQ", "QAT", "AbsmaxObserver", "MovingAverageObserver",
    "QuantedLinear", "FakeQuant", "quant_dequant",
]


class AbsmaxObserver:
    """Per-tensor absmax range observer (reference:
    quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, arr) -> None:
        self._absmax = max(self._absmax,
                           float(jnp.max(jnp.abs(arr))))

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return (self._absmax / qmax) if self._absmax > 0 else 1.0


class MovingAverageObserver(AbsmaxObserver):
    """EMA absmax observer (reference: observers emulating
    moving_average_abs_max)."""

    def __init__(self, quant_bits: int = 8, momentum: float = 0.9):
        super().__init__(quant_bits)
        self.momentum = momentum
        self._seen = False

    def observe(self, arr) -> None:
        cur = float(jnp.max(jnp.abs(arr)))
        if not self._seen:
            self._absmax, self._seen = cur, True
        else:
            self._absmax = (self.momentum * self._absmax
                            + (1 - self.momentum) * cur)


def quant_dequant(arr, scale: float, bits: int = 8):
    """Simulated quantization (round-to-nearest, symmetric)."""
    qmax = 2 ** (bits - 1) - 1
    q = jnp.clip(jnp.round(arr / scale), -qmax - 1, qmax)
    return q * scale


class QuantConfig:
    """Which layers get quantized and with what observers (reference:
    quantization/config.py QuantConfig.add_type_config)."""

    def __init__(self, activation=None, weight=None):
        self._default_act = activation or (lambda: MovingAverageObserver())
        self._default_wt = weight or (lambda: AbsmaxObserver())
        self._type_configs: Dict[Type, dict] = {}

    def add_type_config(self, layer_type: Type, activation=None,
                        weight=None):
        self._type_configs[layer_type] = {
            "activation": activation or self._default_act,
            "weight": weight or self._default_wt,
        }

    def _config_for(self, layer: Layer) -> Optional[dict]:
        from ..nn.layers.common import Linear

        if type(layer) in self._type_configs:
            return self._type_configs[type(layer)]
        if isinstance(layer, Linear) and not self._type_configs:
            # default policy: quantize Linears
            return {"activation": self._default_act,
                    "weight": self._default_wt}
        return None


class _ObservedLinear(Layer):
    """Calibration wrapper: records input/weight ranges each forward."""

    def __init__(self, inner, act_obs, wt_obs):
        super().__init__()
        self.inner = inner
        self.act_obs = act_obs
        self.wt_obs = wt_obs
        self.wt_obs.observe(inner.weight._data)

    def forward(self, x):
        self.act_obs.observe(x._data if isinstance(x, Tensor) else x)
        return self.inner(x)


class QuantedLinear(Layer):
    """Deployed weight-only-int8 Linear: int8 weights + fp scale,
    dequantized into the matmul (reference: the int8 path of
    quantization-converted Linear; TPU-idiomatic weight-only form)."""

    def __init__(self, float_linear, wt_scale: float,
                 act_scale: Optional[float] = None, bits: int = 8):
        super().__init__()
        w = float_linear.weight._data
        qmax = 2 ** (bits - 1) - 1
        self.w_int = jnp.clip(jnp.round(w / wt_scale), -qmax - 1,
                              qmax).astype(jnp.int8)
        self.wt_scale = wt_scale
        self.act_scale = act_scale
        self.bias = float_linear.bias
        self.bits = bits

    def forward(self, x):
        xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        w = self.w_int.astype(xd.dtype) * jnp.asarray(self.wt_scale,
                                                      xd.dtype)
        out = xd @ w
        if self.bias is not None:
            out = out + self.bias._data
        return Tensor(out)


class PTQ:
    """Post-training quantization driver (reference: quantization/ptq.py:
    quantize() instruments, calibration runs observe, convert() deploys)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer) -> Layer:
        from ..nn.layers.common import Linear

        for name, child in list(model.named_children()):
            cfg = self.config._config_for(child)
            if cfg is not None:
                # deployment (QuantedLinear) assumes x @ weight semantics
                if not isinstance(child, Linear):
                    raise NotImplementedError(
                        f"PTQ supports Linear layers; got "
                        f"{type(child).__name__} for {name!r}")
                model.add_sublayer(name, _ObservedLinear(
                    child, cfg["activation"](), cfg["weight"]()))
            else:
                self.quantize(child)
        return model

    def convert(self, model: Layer) -> Layer:
        for name, child in list(model.named_children()):
            if isinstance(child, _ObservedLinear):
                model.add_sublayer(name, QuantedLinear(
                    child.inner, child.wt_obs.scale(),
                    child.act_obs.scale()))
            else:
                self.convert(child)
        return model


class FakeQuant(Layer):
    """Straight-through fake-quant node for QAT (reference: quanters/
    fake_quanter.py — quant-dequant forward, identity gradient)."""

    def __init__(self, bits: int = 8, observer=None):
        super().__init__()
        self.bits = bits
        self.observer = observer or MovingAverageObserver(bits)

    def forward(self, x):
        from ..ops.dispatch import eager_apply, as_tensor_args

        (t,) = as_tensor_args(x)
        if self.training:  # eval passes must not shift the statistics
            self.observer.observe(t._data)
        scale = self.observer.scale()

        def raw(arr):
            q = quant_dequant(arr, scale, self.bits)
            # straight-through: gradient flows as identity
            return arr + jax.lax.stop_gradient(q - arr)

        return eager_apply("fake_quant", raw, [t])


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py):
    wraps eligible layers' inputs+weights with FakeQuant nodes."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer) -> Layer:
        from ..nn.layers.common import Linear

        for name, child in list(model.named_children()):
            cfg = self.config._config_for(child)
            if cfg is not None:
                if not isinstance(child, Linear):
                    raise NotImplementedError(
                        f"QAT supports Linear layers; got "
                        f"{type(child).__name__} for {name!r}")
                model.add_sublayer(name, _QATLinear(
                    child, cfg["activation"](), cfg["weight"]()))
            else:
                self.quantize(child)
        return model

    def convert(self, model: Layer) -> Layer:
        for name, child in list(model.named_children()):
            if isinstance(child, _QATLinear):
                model.add_sublayer(name, QuantedLinear(
                    child.inner, child.wt_fq.observer.scale(),
                    child.act_fq.observer.scale()))
            else:
                self.convert(child)
        return model


class _QATLinear(Layer):
    def __init__(self, inner, act_obs=None, wt_obs=None):
        super().__init__()
        self.inner = inner
        self.act_fq = FakeQuant(observer=act_obs or MovingAverageObserver())
        self.wt_fq = FakeQuant(observer=wt_obs or AbsmaxObserver())

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        xq = self.act_fq(x)
        wq = self.wt_fq(self.inner.weight)
        return F.linear(xq, wq, self.inner.bias)
