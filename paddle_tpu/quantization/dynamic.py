"""Dynamic (per-token) activation quantization for A8W8 serving.

TPU-native equivalent of the activation-quant stage of the reference's
full-int8 serving matmuls (reference:
paddle/fluid/operators/fused/fused_multi_transformer_int8_op.cu — the
quantize round feeding its int8 GEMMs, and the dyquant kernels behind
quant_for_infer). Each activation ROW (one token's features) gets a
symmetric absmax scale computed on the fly — no calibration pass, no
stored statistics — so the skinny decode matmuls can run int8 x int8 on
the MXU with int32 accumulation and a single dequant of the accumulator
by ``act_scale (x) per-output-channel weight_scale``.

Error contract (documented for the parity tests): round-to-nearest
symmetric int8 means each quantized element is off by at most
``scale/2`` where ``scale = absmax(row)/127``, so a K-length dot row is
off by at most ``(absmax(row)/254) * sum_k |w_dequant[k, n]|`` — the
bound ``tests/test_stream_linear_a8w8.py`` checks against an fp32
reference.

Consumers: ``nn/functional/stream_linear.py`` (the int8-activation
streamed GEMM), ``incubate/nn/fused_transformer.py`` (prefill A8W8
matmuls), and ``QuantedLinear(a8w8=True)`` (the PTQ deployment target).

Grouped-decode interaction (r6): the GROUPED weight-stream path
(``stream_layer_tail``) accepts the same int8 stacks + scales but runs
its GEMMs via in-kernel weight dequant (weight-only math) — the int8
weight STREAM (the bound resource) is preserved while the act-quant
int8 x int8 MXU form stays exclusive to the ungrouped kernel, which is
why ``FLAGS_decode_grouped=auto`` keeps A8W8 ungrouped.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["dynamic_act_quant", "int8_dot_dequant"]

#: absmax floor so an all-zero token row quantizes to zeros with a
#: finite scale instead of dividing by zero
ACT_SCALE_EPS = 1e-8


def dynamic_act_quant(x, eps: float = ACT_SCALE_EPS):
    """Per-token symmetric absmax int8 quantization of activations.

    x [..., K] (any float dtype) -> (q int8 [..., K], scale f32 [...])
    with ``q = clip(round(x / scale), -127, 127)`` and
    ``scale = max(absmax(row) / 127, eps)``. Pure function (jit-safe);
    callers count ``quant.act_quant_calls`` at the dispatch layer where
    a per-execution count is honest (inside a traced program this body
    runs once per compile, not per step).
    """
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, eps)
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q, s


def int8_dot_dequant(x_q, x_scale, w_q, w_scale, bias=None,
                     out_dtype=None):
    """int8 x int8 matmul with int32 MXU accumulation + one dequant.

    x_q [..., K] int8, x_scale [...] f32 (per-token), w_q [K, N] int8,
    w_scale [N]-broadcastable f32 (per-output-channel). The accumulator
    dequant is the rank-1 outer product ``x_scale (x) w_scale`` applied
    once on the int32 result (the reference's dequant round after its
    int8 GEMMs); bias (full precision) is added post-dequant.
    """
    acc = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * x_scale[..., None] \
        * w_scale.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out if out_dtype is None else out.astype(out_dtype)
