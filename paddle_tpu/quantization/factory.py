"""Quanter/observer factories + registry.

TPU-native equivalent of the reference's factory layer (reference:
python/paddle/quantization/factory.py — ClassWithArguments,
QuanterFactory, ObserverFactory, the ``quanter()`` class decorator that
registers a quanter under a public name).
"""
from __future__ import annotations

from typing import Dict, Type

__all__ = ["ClassWithArguments", "ObserverFactory", "QuanterFactory",
           "quanter", "observer", "QUANTER_REGISTRY", "OBSERVER_REGISTRY"]

QUANTER_REGISTRY: Dict[str, Type] = {}
OBSERVER_REGISTRY: Dict[str, Type] = {}


class ClassWithArguments:
    """Delayed construction: holds (cls, args, kwargs); ``_instance()``
    builds a fresh object per wrapped layer (factory.py:23)."""

    def __init__(self, cls, *args, **kwargs):
        self._cls = cls
        self._args = args
        self._kwargs = kwargs

    @property
    def cls(self):
        return self._cls

    @property
    def args(self):
        return self._args

    @property
    def kwargs(self):
        return self._kwargs

    def _instance(self):
        return self._cls(*self._args, **self._kwargs)

    def __str__(self):
        kv = ",".join(f"{k}={v}" for k, v in self._kwargs.items())
        return f"{self._cls.__name__}({kv})"

    __repr__ = __str__


class ObserverFactory(ClassWithArguments):
    """(factory.py ObserverFactory)"""


class QuanterFactory(ClassWithArguments):
    """(factory.py QuanterFactory)"""


def _make_factory_class(name, cls, base):
    def __init__(self, *args, **kwargs):
        base.__init__(self, cls, *args, **kwargs)

    return type(name, (base,), {"__init__": __init__})


def quanter(name: str):
    """Class decorator: register a quanter implementation and expose a
    same-named QuanterFactory (reference factory.py ``quanter``)::

        @quanter("MyQuanter")
        class MyQuanterLayer(BaseQuanter): ...

        cfg = QuantConfig(activation=MyQuanter(bits=8), weight=None)
    """

    def deco(cls):
        factory = _make_factory_class(name, cls, QuanterFactory)
        QUANTER_REGISTRY[name] = factory
        import sys

        mod = sys.modules[cls.__module__]
        setattr(mod, name, factory)
        return cls

    return deco


def observer(name: str):
    """Observer counterpart of ``quanter``."""

    def deco(cls):
        factory = _make_factory_class(name, cls, ObserverFactory)
        OBSERVER_REGISTRY[name] = factory
        import sys

        mod = sys.modules[cls.__module__]
        setattr(mod, name, factory)
        return cls

    return deco


def instantiate(f):
    """Accept a factory (``._instance()``), a class/zero-arg callable, or
    an already-built observer/quanter object."""
    if f is None:
        return None
    if hasattr(f, "_instance"):
        return f._instance()
    if callable(f):
        return f()
    return f
