"""Regularizers (reference: python/paddle/regularizer.py — L1Decay/L2Decay
applied to gradients at optimizer time)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]


class WeightDecayRegularizer:
    def __call__(self, param_array, grad_array):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param_array, grad_array):
        return grad_array + self.coeff * jnp.sign(param_array)


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param_array, grad_array):
        return grad_array + self.coeff * param_array
