"""paddle_tpu.serving — SLO-aware request-serving frontend.

The production layer between user traffic and the continuous-batching
engine (ROADMAP item 2): async admission with priorities and bounded
skip-ahead, CHUNKED PREFILL interleaved with decode (long prompts
never stall the decode batch), prefix/KV-cache reuse across requests
sharing a system prompt, and per-request TTFT/TPOT/queue-wait
telemetry. Driven under Poisson load by ``tools/serve_bench.py``.

Observability (PR 9): a request-lifecycle FLIGHT RECORDER
(``journal.py`` — bounded ring journal, ``FLAGS_serve_journal``,
crash-dump-on-exception in ``ServingEngine.run``), an SLO goodput
monitor (``slo.py`` — per-request verdicts, rolling ``slo.goodput`` +
burn rate), and exporters: journal → chrome trace (one lane per
request, rank-stamped for ``tools/trace_merge.py``) and the
``tools/serve_top.py`` live/offline dashboard.

Robustness (ISSUE 11): a deterministic, seeded FAULT-INJECTION
registry (``faults.py`` — named sites in the serving hot path that
raise, delay, corrupt-and-detect, or squeeze the page pool on a
scheduled step) plus the hardening that survives it: per-request
deadlines, crash-isolated stepping with capped-backoff retries, a
progress watchdog, and typed overload shedding — all on one
injectable monotonic clock so every timing behavior tests
deterministically. ``tools/serve_bench.py --chaos`` pins survivor
token parity and bounded goodput loss under a seeded fault schedule.

Fleet serving (ISSUE 14): ``router.py`` scales OUT — a
:class:`FleetRouter` front tier over N replicas with blake2b
prefix-affinity + load/SLO-aware dispatch, heartbeat health checking
(missed-beat → suspect → dead on the same injectable clock), a
per-replica circuit breaker, crash FAILOVER through the
preemption-by-recompute resume path (zero admitted requests lost, and
survivors keep greedy-token parity), graceful DRAIN by page-granular
KV migration, router-tier overload shedding (typed
:class:`FleetOverloaded`), and hedged re-dispatch past suspect
replicas. ``tools/serve_bench.py --fleet N`` benches it;
``tools/serve_top.py --fleet`` renders per-replica health.

The TP (ROADMAP item 1) and EP-MoE (item 4) serving engines plug into
this scheduler: it only talks to the engine's compiled prefill/decode
programs and the page manager, both of which shard underneath it.
"""
from __future__ import annotations

from .adapters import AdapterBank, LoRAAdapter
from .faults import (Clock, DeadlineExceeded, FaultInjector, FaultSpec,
                     FleetOverloaded, InjectedFault, ManualClock,
                     PoolSizingError, ReplicaKilled, ServerOverloaded,
                     TenantQuotaExceeded, TokenCorruption,
                     WatchdogTimeout, set_clock, use_clock)
from .host_tier import HostKVTier
from .journal import FlightRecorder
from .prefix_cache import PrefixCache
from .request import Request
from .router import CircuitBreaker, FleetRouter, Replica
from .scheduler import ServingEngine, SLOConfig
from .slo import SLOMonitor

__all__ = ["Request", "PrefixCache", "HostKVTier",
           "ServingEngine", "SLOConfig",
           "FlightRecorder", "SLOMonitor",
           "FleetRouter", "Replica", "CircuitBreaker",
           "AdapterBank", "LoRAAdapter",
           "FaultInjector", "FaultSpec", "Clock", "ManualClock",
           "set_clock", "use_clock", "InjectedFault", "TokenCorruption",
           "DeadlineExceeded", "ServerOverloaded", "WatchdogTimeout",
           "PoolSizingError", "ReplicaKilled", "FleetOverloaded",
           "TenantQuotaExceeded"]
