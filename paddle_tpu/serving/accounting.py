"""Per-request -> per-tenant usage metering: the serving ledger.

ROADMAP item 2 (thousands of tenants on shared base weights) needs
per-tenant quotas and weighted-fair admission — none of which can be
enforced before it can be *measured*. PR 16 built fleet-wide
continuous telemetry, but every metric is engine-global: nothing says
which request (or tenant) consumed the device time, the KV pages, or
the queue. The :class:`UsageLedger` closes that gap by partitioning
the existing ``serve.step`` phase attribution (PR 16's
``serve.step.{prefill_chunk,decode_chunk,spec_verify,migration}_ms``
stamps) across the requests each phase actually served, and by
integrating KV **page-seconds** per request through every page-count
transition (grow / truncate / preempt / migrate / prefix-share).

Attribution rules
-----------------

- **prefill chunk** -> the one request the chunk prefilled.
- **decode / spec-verify chunk** -> split over the active slots the
  chunk advanced (emitted >= 1 token); if no slot advanced, split
  over every slot that was active when the chunk started. Wasted
  chunk-tail tokens (``serving.wasted_decode_tokens``) are charged —
  as token counts — to the request that finished mid-chunk.
- **migration** -> the migrated request, on the DESTINATION ledger.
- **admit / host overhead** phases are scheduler bookkeeping, not
  work done *for* a request — they are deliberately not attributed.

Conservation is the headline property and it is engineered to be
EXACT, not approximate:

- Every charge call receives the *same float expression from the same
  clock stamps* as the ``serve.step.*_ms`` histogram observation, and
  the ledger accumulates those floats in the same order — so on a
  single engine the ledger's per-phase float totals are **bitwise
  equal** to the histogram totals.
- Per-request shares are kept in **integer nanoseconds**: a chunk's
  ``round(ms * 1e6)`` ns are split with ``divmod`` (the first
  ``remainder`` requests get one extra ns), so the shares *partition*
  the phase total exactly — under any split counts, any summation
  order, chaos, preemption, or fleet failover.

Cardinality bounds: the ledger keys records by request id (one small
``__slots__`` record per request of the run) and exports **bounded**
tenant gauges — ``tenant.{count,max_share}`` plus index-keyed
``tenant.top<i>.device_ms`` for the top-K tenants only — never one
metric key per tenant. Tenant *names* ride in the usage JSONL and the
``serve_top --tenants`` view, not in the metric registry.

Tenant semantics: ``Request(tenant=...)`` stamps the tenant; a
request without one bills to ``default``. A failed-over or migrated
request keeps its rid, so the fleet fold (:func:`fold_records`) sums
its per-replica charges into ONE fleet record — charged exactly once.

Like ``serving/journal.py`` this module is stdlib-only at import time
so ``tools/serve_top.py`` and ``tools/trace_merge.py`` can load it
standalone for offline post-mortems without paying the jax import.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

try:  # the serving clock seam (serving/faults.py): page-second
    # integrals follow the same injectable monotonic clock as the
    # step attribution stamps, so ManualClock tests see one timeline.
    from .faults import now as _now
except ImportError:  # standalone load — real monotonic clock
    _now = time.monotonic

__all__ = ["UsageLedger", "WORK_PHASES", "DEFAULT_TENANT",
           "fold_records", "tenant_rollup", "load_usage_jsonl",
           "unattributed_ms"]

#: the serve.step phases the ledger partitions across requests
#: (``admit``/``host_overhead`` are scheduler bookkeeping — excluded)
WORK_PHASES = ("prefill_chunk", "decode_chunk", "spec_verify",
               "migration")

#: tenant billed when ``Request.tenant`` is None
DEFAULT_TENANT = "default"

#: integer count fields carried on every record (summed by the fold)
COUNT_FIELDS = ("prefill_tokens", "decode_tokens",
                "spec_accepted_tokens", "wasted_tokens", "retries",
                "preemptions", "requeues", "prefix_pages_saved")

#: terminal states a usage record can close with (``unserved`` =
#: submitted but never admitted before the serve loop exited)
TERMINAL_STATES = ("ok", "error", "deadline_exceeded", "shed",
                   "unserved")

#: fold precedence when hops disagree (lower wins): a request one
#: replica's admission check shed can still finish ``ok`` on the
#: dispatch retry's next candidate — the completed state is the
#: fleet truth, and ``shed``/``unserved`` only stand when nothing
#: stronger happened anywhere
_STATE_RANK = {"ok": 0, "deadline_exceeded": 1, "error": 2,
               "unserved": 3, "shed": 4, None: 9}


class _ReqUsage:
    """One request's running totals (mutable, ``__slots__``-packed)."""

    __slots__ = ("rid", "tenant", "adapter", "state", "phase_ns",
                 "queue_s", "kv_page_s", "pages", "pages_ts",
                 "prefill_tokens", "decode_tokens",
                 "spec_accepted_tokens", "wasted_tokens", "retries",
                 "preemptions", "requeues", "prefix_pages_saved")

    def __init__(self, rid: int, tenant: str, ts: float,
                 adapter: Optional[str] = None):
        self.rid = rid
        self.tenant = tenant
        self.adapter = adapter
        self.state: Optional[str] = None
        self.phase_ns: Dict[str, int] = {}
        self.queue_s = 0.0
        self.kv_page_s = 0.0
        self.pages = 0
        self.pages_ts = ts
        self.prefill_tokens = 0
        self.decode_tokens = 0
        self.spec_accepted_tokens = 0
        self.wasted_tokens = 0
        self.retries = 0
        self.preemptions = 0
        self.requeues = 0
        self.prefix_pages_saved = 0

    @property
    def device_ns(self) -> int:
        return sum(self.phase_ns.values())

    def as_record(self, hop: Optional[int] = None) -> dict:
        d = {"type": "usage", "rid": self.rid, "tenant": self.tenant,
             "state": self.state,
             **({"adapter": self.adapter}
                if self.adapter is not None else {}),
             "phase_ns": dict(self.phase_ns),
             "device_ms": round(self.device_ns / 1e6, 6),
             "queue_s": round(self.queue_s, 9),
             "kv_page_s": round(self.kv_page_s, 9)}
        for f in COUNT_FIELDS:
            d[f] = getattr(self, f)
        if hop is not None:
            d["hop"] = hop
        return d


class UsageLedger:
    """Clock-seam-driven per-request -> per-tenant resource ledger.

    Default-off (``FLAGS_usage_ledger``): the engine holds
    ``usage = None`` and every hook is a single attribute test — zero
    per-step allocations, pinned like the PR 9 journal-off test.
    """

    def __init__(self, default_tenant: str = DEFAULT_TENANT,
                 clock=None):
        self.default_tenant = default_tenant
        self._clock = clock if clock is not None else _now
        self._lock = threading.Lock()
        self._recs: Dict[int, _ReqUsage] = {}
        # per-phase conservation counters: float ms accumulated with
        # the histogram's exact values/order, counts, and the integer
        # ns actually partitioned across requests
        self._phase_ms: Dict[str, float] = {}
        self._phase_count: Dict[str, int] = {}
        self._phase_ns: Dict[str, int] = {}
        # defensive: ns charged with an empty target list (should not
        # happen; kept out of any tenant but inside the phase total)
        self._system_ns: Dict[str, int] = {}

    # ---------------- record access ----------------

    def _rec(self, req) -> _ReqUsage:
        rid = int(req.id)
        rec = self._recs.get(rid)
        if rec is None:
            tenant = getattr(req, "tenant", None)
            rec = _ReqUsage(rid, tenant if tenant is not None
                            else self.default_tenant, self._clock(),
                            adapter=getattr(req, "adapter_id", None))
            self._recs[rid] = rec
        return rec

    def record_of(self, rid: int) -> Optional[dict]:
        with self._lock:
            rec = self._recs.get(int(rid))
            return None if rec is None else rec.as_record()

    def records(self, include_open: bool = True,
                hop: Optional[int] = None) -> List[dict]:
        """Every record, rid-ordered. ``include_open=False`` keeps
        only terminally-closed records; ``hop`` stamps the producing
        replica index (the fold's dedup key)."""
        with self._lock:
            recs = [self._recs[r] for r in sorted(self._recs)]
            return [r.as_record(hop) for r in recs
                    if include_open or r.state is not None]

    # ---------------- device-time attribution ----------------

    def charge_phase(self, phase: str, ms: float, reqs) -> None:
        """Attribute one phase observation across ``reqs``.

        ``ms`` MUST be the same float the ``serve.step.<phase>_ms``
        histogram observes (same clock stamps, same expression) — the
        conservation invariant depends on it. The integer-ns split
        partitions ``round(ms * 1e6)`` exactly across the targets."""
        total_ns = round(float(ms) * 1e6)
        with self._lock:
            self._phase_ms[phase] = \
                self._phase_ms.get(phase, 0.0) + float(ms)
            self._phase_count[phase] = \
                self._phase_count.get(phase, 0) + 1
            self._phase_ns[phase] = \
                self._phase_ns.get(phase, 0) + total_ns
            n = len(reqs)
            if n == 0:
                self._system_ns[phase] = \
                    self._system_ns.get(phase, 0) + total_ns
                return
            q, r = divmod(total_ns, n)
            for i, req in enumerate(reqs):
                rec = self._rec(req)
                rec.phase_ns[phase] = rec.phase_ns.get(phase, 0) \
                    + q + (1 if i < r else 0)

    # ---------------- KV page-seconds ----------------

    def set_pages(self, req, n: int, now: Optional[float] = None) \
            -> None:
        """Mark ``req`` as holding ``n`` KV pages from now on,
        integrating ``pages held x elapsed clock`` since the previous
        transition. Called at every page-count change: prefix-share
        at admission (each sharer charged independently), prefill
        grow, decode grow, speculative truncate, preempt/requeue
        free, release, migration import, and crash detach."""
        t = self._clock() if now is None else now
        with self._lock:
            rec = self._rec(req)
            if rec.pages:
                rec.kv_page_s += rec.pages * (t - rec.pages_ts)
            rec.pages = int(n)
            rec.pages_ts = t

    # ---------------- counts ----------------

    def note_queue(self, req, seconds: float) -> None:
        with self._lock:
            self._rec(req).queue_s += float(seconds)

    def add_tokens(self, req, prefill: int = 0, decode: int = 0,
                   spec_accepted: int = 0, wasted: int = 0) -> None:
        with self._lock:
            rec = self._rec(req)
            rec.prefill_tokens += prefill
            rec.decode_tokens += decode
            rec.spec_accepted_tokens += spec_accepted
            rec.wasted_tokens += wasted

    def add_event(self, req, retry: int = 0, preempt: int = 0,
                  requeue: int = 0) -> None:
        with self._lock:
            rec = self._rec(req)
            rec.retries += retry
            rec.preemptions += preempt
            rec.requeues += requeue

    def credit_prefix(self, req, pages: int) -> None:
        with self._lock:
            self._rec(req).prefix_pages_saved += int(pages)

    # ---------------- terminal close ----------------

    def finish(self, req, state: str) -> Optional[dict]:
        """Close ``req``'s record with a terminal state, EXACTLY
        ONCE: a second close is a no-op returning None (the caller
        skips re-journaling). Returns a snapshot dict for the journal
        terminal event; charges from the very chunk that finished the
        request may still land after the close — exports read the
        final accumulated values, the snapshot is as-of-close."""
        t = self._clock()
        with self._lock:
            rec = self._rec(req)
            if rec.state is not None:
                return None
            if rec.pages:   # close the page-second integral
                rec.kv_page_s += rec.pages * (t - rec.pages_ts)
                rec.pages = 0
            rec.pages_ts = t
            rec.state = state
            return rec.as_record()

    # ---------------- conservation / views ----------------

    def attributed_ms(self) -> Dict[str, float]:
        """Per-phase float ms totals, accumulated with the exact
        values (and order) the ``serve.step.*_ms`` histograms saw."""
        with self._lock:
            return dict(self._phase_ms)

    def phase_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._phase_count)

    def phase_ns_totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._phase_ns)

    def system_ns_totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._system_ns)

    def tenant_totals(self) -> Dict[str, dict]:
        """Per-tenant rollup of every record (open + closed)."""
        return tenant_rollup(self.records(include_open=True))

    def top_tenants(self, k: int) -> List[Tuple[str, int]]:
        """Top-``k`` tenants by attributed device ns, descending."""
        with self._lock:
            by_t: Dict[str, int] = {}
            for rec in self._recs.values():
                by_t[rec.tenant] = by_t.get(rec.tenant, 0) \
                    + rec.device_ns
        return sorted(by_t.items(), key=lambda kv: (-kv[1], kv[0]))[
            :max(int(k), 0)]

    def tenant_count(self) -> int:
        with self._lock:
            return len({r.tenant for r in self._recs.values()})

    def max_share(self) -> float:
        """Largest single tenant's share of attributed device time
        (0.0 before any attribution)."""
        top = self.top_tenants(1)
        with self._lock:
            total = sum(r.device_ns for r in self._recs.values())
        if not top or total <= 0:
            return 0.0
        return top[0][1] / total

    def publish_gauges(self, top_k: int = 4) -> None:
        """Bounded tenant gauges for the Prometheus/timeseries path:
        ``tenant.{count,max_share}`` + index-keyed (NOT name-keyed —
        the cardinality bound) ``tenant.top<i>.device_ms``."""
        from paddle_tpu.profiler import stats as _stats

        with self._lock:
            closed = sum(r.state is not None
                         for r in self._recs.values())
        _stats.set_gauge("usage.records", closed)
        _stats.set_gauge("tenant.count", self.tenant_count())
        _stats.set_gauge("tenant.max_share",
                         round(self.max_share(), 4))
        for i, (_, ns) in enumerate(self.top_tenants(top_k)):
            _stats.set_gauge(f"tenant.top{i}.device_ms",
                             round(ns / 1e6, 3))

    def reset(self) -> None:
        """Forget everything (bench warmup boundary)."""
        with self._lock:
            self._recs.clear()
            self._phase_ms.clear()
            self._phase_count.clear()
            self._phase_ns.clear()
            self._system_ns.clear()

    # ---------------- exporters ----------------

    def dump_jsonl(self, path: str, hop: Optional[int] = None,
                   include_open: bool = True) -> str:
        """Append-only usage JSONL: one ``{"type": "usage", ...}``
        line per request (tools/serve_top.py --tenants offline input;
        tools/trace_merge.py folds multi-replica dumps)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for rec in self.records(include_open=include_open,
                                    hop=hop):
                f.write(json.dumps(rec) + "\n")
        return path


# ---------------- module-level fold / rollup helpers ----------------


def load_usage_jsonl(path: str) -> List[dict]:
    """Parse one usage JSONL artifact (``type=usage`` lines only)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if d.get("type", "usage") == "usage":
                out.append(d)
    return out


def fold_records(records: Iterable[dict]) -> List[dict]:
    """Fold multi-replica usage records into ONE record per request.

    Dedup on ``(hop, rid)`` (the same replica dump merged twice
    contributes once), then sum per ``(tenant, rid)``: integer
    ``phase_ns`` / token / event counts add exactly, ``queue_s`` and
    ``kv_page_s`` add, and the terminal ``state`` resolves by
    ``_STATE_RANK`` precedence across hops — a failed-over or
    migrated request is charged exactly once fleet-wide."""
    seen = set()
    by_rid: Dict[Tuple[str, int], dict] = {}
    for rec in records:
        key = (rec.get("hop"), rec.get("rid"))
        if key[0] is not None and key in seen:
            continue
        seen.add(key)
        rk = (rec.get("tenant", DEFAULT_TENANT), int(rec["rid"]))
        out = by_rid.get(rk)
        if out is None:
            out = by_rid[rk] = {
                "type": "usage", "rid": rk[1], "tenant": rk[0],
                "state": None, "phase_ns": {}, "queue_s": 0.0,
                "kv_page_s": 0.0, "hops": 0}
            for f in COUNT_FIELDS:
                out[f] = 0
        out["hops"] += 1
        for ph, ns in (rec.get("phase_ns") or {}).items():
            out["phase_ns"][ph] = out["phase_ns"].get(ph, 0) + int(ns)
        out["queue_s"] += float(rec.get("queue_s", 0.0))
        out["kv_page_s"] += float(rec.get("kv_page_s", 0.0))
        for f in COUNT_FIELDS:
            out[f] += int(rec.get(f, 0))
        st = rec.get("state")
        if _STATE_RANK.get(st, 9) < _STATE_RANK.get(out["state"], 9):
            out["state"] = st
        if rec.get("adapter") is not None \
                and out.get("adapter") is None:
            # adapter id rides the fold — a failed-over adaptered
            # request keeps its stamp in the merged fleet view
            out["adapter"] = rec["adapter"]
    folded = [by_rid[k] for k in sorted(by_rid, key=lambda t: t[1])]
    for out in folded:
        out["device_ms"] = round(
            sum(out["phase_ns"].values()) / 1e6, 6)
        out["queue_s"] = round(out["queue_s"], 9)
        out["kv_page_s"] = round(out["kv_page_s"], 9)
    return folded


def tenant_rollup(records: Iterable[dict]) -> Dict[str, dict]:
    """Aggregate (possibly folded) usage records per tenant; the
    ``serve_top --tenants`` table rows. ``waste_share`` = wasted /
    (decode + wasted) tokens — the satellite's per-tenant waste
    surface."""
    by_t: Dict[str, dict] = {}
    for rec in records:
        t = rec.get("tenant", DEFAULT_TENANT)
        agg = by_t.get(t)
        if agg is None:
            agg = by_t[t] = {"tenant": t, "n_requests": 0,
                             "device_ms": 0.0, "device_ns": 0,
                             "queue_s": 0.0, "kv_page_s": 0.0,
                             "states": {}, "adapters": set()}
            for f in COUNT_FIELDS:
                agg[f] = 0
        agg["n_requests"] += 1
        if rec.get("adapter") is not None:
            agg["adapters"].add(rec["adapter"])
        agg["device_ns"] += sum(
            (rec.get("phase_ns") or {}).values())
        agg["queue_s"] += float(rec.get("queue_s", 0.0))
        agg["kv_page_s"] += float(rec.get("kv_page_s", 0.0))
        for f in COUNT_FIELDS:
            agg[f] += int(rec.get(f, 0))
        st = rec.get("state") or "open"
        agg["states"][st] = agg["states"].get(st, 0) + 1
    total_ns = sum(a["device_ns"] for a in by_t.values())
    for agg in by_t.values():
        agg["adapters"] = sorted(agg["adapters"])
        agg["device_ms"] = round(agg["device_ns"] / 1e6, 6)
        agg["share"] = (agg["device_ns"] / total_ns
                        if total_ns > 0 else 0.0)
        den = agg["decode_tokens"] + agg["wasted_tokens"]
        agg["waste_share"] = (agg["wasted_tokens"] / den
                              if den > 0 else 0.0)
    return by_t


def unattributed_ms(*ledgers) -> float:
    """Device time the ``serve.step`` work-phase histograms saw but
    no ledger attributed — an accounting leak; healthy runs report
    exactly ``0.0`` (gated UP with no noise floor by bench_gate).
    Reads the process stats registry, so pass every live ledger
    (fleet: one per replica + the router's)."""
    from paddle_tpu.profiler import stats as _stats

    _, _, hists = _stats.sample_values()
    leak = 0.0
    for phase in WORK_PHASES:
        h = hists.get(f"serve.step.{phase}_ms")
        total = float(h[1]) if h else 0.0
        attributed = sum(
            l.attributed_ms().get(phase, 0.0) for l in ledgers
            if l is not None)
        leak += max(0.0, total - attributed)
    return round(leak, 3)
