"""AdapterBank: paged multi-LoRA weight banks with hot swap (ISSUE 18).

Thousands of tenants share ONE base weight stream; what differs per
tenant is a low-rank delta per target projection (qkv / o / ffn1 /
ffn2). This module owns the serving-side adapter state:

- **Paged, rank-padded banks.** Per projection the bank holds two
  layer-stacked arrays ``A [L, S, K, R]`` and ``B [L, S, R, N]`` over
  ``S`` fixed SLOTS (the paging unit — an adapter occupies one slot,
  load/unload writes one slot's page, nothing else moves). ``R`` is
  the configured ``rank`` padded to the weight dtype's sublane tile
  (``nn/functional/lora.py pad_rank``; int8: 32, bf16: 16, f32: 8);
  adapters with a smaller rank zero-fill the padded columns, which
  contribute exact +0.0 in the delta kernel. The LoRA scale
  ``alpha / rank`` is folded into ``B`` at load time, so the serve
  path never multiplies by it.

- **Hot load/unload under live traffic, refcounted.** ``load`` writes
  a free slot's page and bumps the bank VERSION (the engine re-
  device-puts the bank operands lazily on version change — array
  SHAPES never change, so no compiled program is invalidated and no
  engine restart happens). ``acquire(name, rid)`` pins the adapter
  for one request; ``release(rid)`` unpins (idempotent — every
  terminal path calls it defensively). ``unload`` with live
  references marks the slot DRAINING: new acquires are rejected
  (typed ``KeyError``), live requests keep decoding against the still-
  resident page, and the slot frees the moment its refcount hits
  zero. An adapter is never ripped out from under an active slot.

- **Shareable across fleet replicas.** The bank is a host-side object
  (numpy master copy + per-engine device cache); every replica of a
  fleet can hold the same bank, so failover/migration of an adaptered
  request needs no weight movement — the request's ``adapter_id``
  resolves on the destination replica's identical bank.

Telemetry: ``lora.swaps`` counts completed load/unload events,
``lora.active_adapters`` gauges loaded non-draining slots (both under
the ``lora.`` prefix in ``CONVENTION_PREFIXES``).

TP composition (distributed/tp.py ``_ADAPTER_LAYOUT``): A of the
column-parallel projections (qkv, ffn1) replicates while their B
column-splits ``[L, S, R, N/mp]`` alongside the base shards (qkv B
takes the SAME column gather as the base qkv stack); A of the
row-parallel projections (o, ffn2) row-splits ``[L, S, K/mp, R]``
while their B replicates — ``x·A = Σ_shards x_s·A_s``, so the delta
partial joins the base partial BEFORE the layer's existing psum and
the trace-pinned 2 psums/layer survive with no new collectives.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..profiler import stats as _stats

__all__ = ["LoRAAdapter", "AdapterBank", "TARGET_PROJECTIONS"]

#: target projection -> (stacked base weight it rides on)
TARGET_PROJECTIONS = ("qkv", "out", "ffn1", "ffn2")


class LoRAAdapter:
    """One tenant's LoRA weights, host-side.

    ``weights``: dict ``projection -> (A [L, K, r], B [L, r, N])``
    over any subset of :data:`TARGET_PROJECTIONS` (missing projections
    contribute zero delta). ``alpha`` defaults to ``rank`` (scale 1);
    the ``alpha / rank`` scale is folded into B here, once.
    """

    def __init__(self, name: str, rank: int, weights: Dict[str, tuple],
                 alpha: Optional[float] = None):
        self.name = str(name)
        self.rank = int(rank)
        scale = 1.0 if alpha is None else float(alpha) / self.rank
        self.weights = {}
        for proj, (a, b) in weights.items():
            if proj not in TARGET_PROJECTIONS:
                raise ValueError(
                    f"LoRAAdapter {name!r}: unknown projection "
                    f"{proj!r} (targets: {TARGET_PROJECTIONS})")
            a = np.asarray(a)
            b = np.asarray(b)
            if a.ndim != 3 or b.ndim != 3 or a.shape[-1] != self.rank \
                    or b.shape[1] != self.rank:
                raise ValueError(
                    f"LoRAAdapter {name!r}/{proj}: need A [L, K, r], "
                    f"B [L, r, N] at rank {self.rank}, got "
                    f"{a.shape} / {b.shape}")
            self.weights[proj] = (a, b * scale if scale != 1.0 else b)


class AdapterBank:
    """Paged, refcounted multi-LoRA bank for one model's serve stack.

    ``dims``: dict ``projection -> (K, N)`` (use :meth:`from_stack` to
    derive it from the engine's stacked weights). ``slots``: bank
    capacity — the ONLY per-adapter-count allocation; the delta path's
    compiled programs depend on ``(S, R)`` shapes, never on which
    adapters occupy the slots.
    """

    def __init__(self, num_layers: int, dims: Dict[str, tuple], *,
                 slots: int = 8, rank: int = 8, dtype=None):
        import jax.numpy as jnp

        from ..nn.functional.lora import pad_rank

        if slots < 1:
            raise ValueError("AdapterBank needs at least one slot")
        self.num_layers = int(num_layers)
        self.slots = int(slots)
        self.rank = int(rank)
        self.dtype = jnp.dtype(dtype or jnp.float32)
        self.rank_pad = pad_rank(self.rank, self.dtype)
        self.dims = {p: (int(k), int(n)) for p, (k, n) in dims.items()
                     if p in TARGET_PROJECTIONS}
        if not self.dims:
            raise ValueError("AdapterBank: no target projections")
        L, S, R = self.num_layers, self.slots, self.rank_pad
        # host master copy; written in place on load/unload
        self._a = {p: np.zeros((L, S, k, R), self.dtype)
                   for p, (k, n) in self.dims.items()}
        self._b = {p: np.zeros((L, S, R, n), self.dtype)
                   for p, (k, n) in self.dims.items()}
        self._lock = threading.RLock()
        self._slot_of: Dict[str, int] = {}
        self._free = list(range(self.slots))
        self._refs: Dict[str, int] = {}
        self._draining: Dict[str, bool] = {}
        self._rid_name: Dict[object, str] = {}
        self._version = 0
        self._dev = None            # (version, tp, operand dict)

    # ------------- construction helpers -------------

    @classmethod
    def from_stack(cls, weights: Dict, *, slots: int = 8,
                   rank: int = 8, dtype=None) -> "AdapterBank":
        """Derive projection dims from an engine's stacked weights
        (``qkv_weight [L, d, Nq]`` etc.; MoE stacks have no ffn1/ffn2
        targets — their experts are already per-token routed)."""
        dims = {}
        L = None
        for proj in TARGET_PROJECTIONS:
            w = weights.get(f"{proj}_weight")
            if w is None:
                continue
            L = int(w.shape[0])
            dims[proj] = (int(w.shape[1]), int(w.shape[2]))
        if L is None:
            raise ValueError(
                "AdapterBank.from_stack: no stacked *_weight entries")
        if dtype is None:
            dtype = np.asarray(weights[next(
                f"{p}_weight" for p in TARGET_PROJECTIONS
                if f"{p}_weight" in weights)]).dtype
            if np.dtype(dtype) == np.int8:   # quantized base stack:
                dtype = None                 # adapters stay fp32
        return cls(L, dims, slots=slots, rank=rank, dtype=dtype)

    def random_adapter(self, name: str, rank: Optional[int] = None,
                       seed: int = 0, init_scale: float = 0.02,
                       projections=None) -> LoRAAdapter:
        """A random adapter matching this bank's dims (tests/bench)."""
        rank = self.rank if rank is None else int(rank)
        if rank > self.rank_pad:
            raise ValueError(
                f"rank {rank} exceeds bank rank_pad {self.rank_pad}")
        rng = np.random.default_rng(
            np.uint32(hash((name, seed)) & 0xFFFFFFFF))
        w = {}
        for proj, (k, n) in self.dims.items():
            if projections is not None and proj not in projections:
                continue
            a = rng.standard_normal((self.num_layers, k, rank)) \
                * init_scale
            b = rng.standard_normal((self.num_layers, rank, n)) \
                * init_scale
            w[proj] = (a.astype(np.float32), b.astype(np.float32))
        return LoRAAdapter(name, rank, w)

    # ------------- hot load / unload -------------

    def load(self, adapter: LoRAAdapter) -> int:
        """Write ``adapter`` into a free slot (hot: version bump only,
        no shape change, no engine restart). Returns the slot."""
        with self._lock:
            if adapter.name in self._slot_of:
                raise ValueError(
                    f"adapter {adapter.name!r} is already loaded"
                    + (" (draining)" if self._draining.get(adapter.name)
                       else ""))
            if adapter.rank > self.rank_pad:
                raise ValueError(
                    f"adapter {adapter.name!r} rank {adapter.rank} "
                    f"exceeds bank rank_pad {self.rank_pad}")
            if not self._free:
                pinned = {n: self._refs.get(n, 0)
                          for n in self._slot_of}
                raise RuntimeError(
                    f"AdapterBank full ({self.slots} slots); "
                    f"loaded: {pinned} — unload one first")
            slot = self._free.pop(0)
            for proj in self.dims:
                a_bank = self._a[proj]
                b_bank = self._b[proj]
                a_bank[:, slot] = 0
                b_bank[:, slot] = 0
                if proj in adapter.weights:
                    a, b = adapter.weights[proj]
                    a_bank[:, slot, :, :adapter.rank] = a
                    b_bank[:, slot, :adapter.rank, :] = b
            self._slot_of[adapter.name] = slot
            self._refs[adapter.name] = 0
            self._draining[adapter.name] = False
            self._version += 1
            _stats.inc("lora.swaps")
            self._publish()
            return slot

    def unload(self, name: str) -> bool:
        """Unload ``name``. With live references the slot DRAINS: new
        acquires are rejected, live requests keep their weights, and
        the slot frees at refcount zero. Returns True when the slot
        was freed now, False when draining."""
        with self._lock:
            if name not in self._slot_of:
                raise KeyError(f"adapter {name!r} is not loaded")
            if self._refs.get(name, 0) > 0:
                self._draining[name] = True
                self._publish()
                return False
            self._free_slot(name)
            return True

    def _free_slot(self, name: str) -> None:
        # lock held
        slot = self._slot_of.pop(name)
        for proj in self.dims:
            self._a[proj][:, slot] = 0
            self._b[proj][:, slot] = 0
        self._refs.pop(name, None)
        self._draining.pop(name, None)
        self._free.append(slot)
        self._free.sort()
        self._version += 1
        _stats.inc("lora.swaps")
        self._publish()

    # ------------- per-request pinning -------------

    def acquire(self, name: str, rid) -> int:
        """Pin ``name`` for request ``rid``; returns its slot. Raises
        ``KeyError`` for unknown or draining adapters (the submit path
        surfaces it to the caller before admission)."""
        with self._lock:
            if name not in self._slot_of:
                raise KeyError(f"adapter {name!r} is not loaded")
            if self._draining.get(name):
                raise KeyError(f"adapter {name!r} is draining "
                               "(unload pending)")
            prev = self._rid_name.get(rid)
            if prev == name:
                return self._slot_of[name]
            if prev is not None:
                self._release_name(prev)
            self._rid_name[rid] = name
            self._refs[name] = self._refs.get(name, 0) + 1
            return self._slot_of[name]

    def release(self, rid) -> None:
        """Unpin whatever ``rid`` holds (idempotent — every terminal
        request path calls this defensively)."""
        with self._lock:
            name = self._rid_name.pop(rid, None)
            if name is not None:
                self._release_name(name)

    def _release_name(self, name: str) -> None:
        # lock held
        if name not in self._refs:
            return
        self._refs[name] = max(self._refs[name] - 1, 0)
        if self._refs[name] == 0 and self._draining.get(name):
            self._free_slot(name)

    # ------------- inspection -------------

    def slot_of(self, name: str) -> int:
        with self._lock:
            return self._slot_of[name]

    def refcount(self, name: str) -> int:
        with self._lock:
            return self._refs.get(name, 0)

    def loaded(self):
        """name -> slot of every resident adapter (draining included)."""
        with self._lock:
            return dict(self._slot_of)

    def is_draining(self, name: str) -> bool:
        with self._lock:
            return bool(self._draining.get(name))

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def _publish(self) -> None:
        # lock held
        active = sum(1 for n in self._slot_of
                     if not self._draining.get(n))
        _stats.set_gauge("lora.active_adapters", active)

    # ------------- device operands -------------

    def operands(self, tp=None) -> Dict[str, object]:
        """The traced bank operands for one dispatch: ``{proj}_a`` /
        ``{proj}_b`` device arrays (re-``device_put`` lazily when the
        bank version moved — a hot swap changes VALUES only, so the
        compiled programs survive). Under TP the arrays are placed per
        ``distributed/tp.py _ADAPTER_LAYOUT`` (qkv B takes the base
        stack's column gather)."""
        import jax

        with self._lock:
            version = self._version
            if self._dev is not None and self._dev[0] == version \
                    and self._dev[1] is tp:
                return self._dev[2]
            host = {}
            for proj in self.dims:
                host[f"{proj}_a"] = self._a[proj].copy()
                host[f"{proj}_b"] = self._b[proj].copy()
        if tp is None:
            dev = {k: jax.device_put(v) for k, v in host.items()}
        else:
            if "qkv_b" in host and tp.mp > 1:
                host["qkv_b"] = np.take(
                    host["qkv_b"], tp.qkv_col_index(), axis=-1)
            dev = {k: jax.device_put(
                       v, tp.sharding(*tp.adapter_spec(k)))
                   for k, v in host.items()}
        with self._lock:
            self._dev = (version, tp, dev)
        return dev
