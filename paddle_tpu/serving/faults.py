"""Deterministic fault injection + the serving clock seam.

A serving stack's recovery paths (eviction, stall/requeue, preemption,
retry, shed) are exactly the code that never runs in a green test
suite. This module makes them DRIVABLE: a seeded, scheduled fault
registry with named sites wired into the serving hot path, and the one
injectable monotonic clock every serving/SLO/journal timestamp routes
through, so deadline/backoff/watchdog behavior is tested by advancing
a number instead of sleeping.

Sites (each a named choke point; the owner calls ``fire()`` with its
per-site hit counter advancing once per call):

- ``kv.alloc`` / ``kv.grow`` — page-pool allocation and on-demand
  growth (``inference/kv_cache.py``);
- ``prefill.dispatch`` — one chunk-prefill program dispatch
  (``serving/scheduler.py``; ``corrupt`` specs poke the chunk's
  emitted token);
- ``decode.step`` — one continuous-batching decode chunk
  (``inference/engine.py``; ``corrupt`` specs poke the token matrix
  BEFORE any request state mutates, so detection → retry is clean);
- ``prefix.insert`` — prefix-cache registration
  (``serving/prefix_cache.py``; failures are absorbed, never fatal);
- ``journal.dump`` — crash-dump/journal export (``crash_dump`` must
  never let a failed dump mask the original exception);
- ``router.dispatch`` — one fleet-router dispatch attempt
  (``serving/router.py``; a raise counts against the chosen
  replica's circuit breaker and the router retries a healthy peer);
- ``replica.step`` — one fleet replica's scheduler step (the
  ``kill``/``hang`` kinds live here: a kill crashes the replica's
  serve loop, a hang wedges it long enough to miss heartbeats);
- ``replica.heartbeat`` — a replica's per-loop heartbeat stamp (a
  raise SUPPRESSES that beat, so the health checker's
  missed-beat → suspect → dead machine is drivable without killing
  the replica).

Fault kinds per scheduled hit:

- ``raise``   — raise :class:`InjectedFault` (or a caller-supplied
  exception instance) at the site;
- ``delay``   — sleep ``delay_ms`` through the injected clock (a
  ManualClock makes this a pure time-warp);
- ``corrupt`` — corrupt the site's value (token id) so the stack's
  DETECTION (token-range validation) fires, not a silent wrong
  answer;
- ``squeeze`` — seize ``pages`` free pool pages under a fault-owned
  key (deterministic pool exhaustion: the engine's REAL recovery
  paths — cold-prefix eviction, prefill stall/requeue,
  preemption-by-recompute — engage on the genuine free-list state);
- ``release`` — free every squeezed page;
- ``kill``    — raise :class:`ReplicaKilled` at the site (the fleet
  replica serve loop treats it as a process crash: the loop exits,
  heartbeats stop, and the router fails its requests over);
- ``hang``    — sleep ``delay_ms`` (default 30 s) through the
  injected clock: the replica wedges mid-step, misses beats, and the
  health checker walks it suspect → dead while it sleeps (a
  ManualClock makes the wedge a pure time-warp).

Scheduling is deterministic: ``at`` (hit index or indices), ``every``
(every k-th hit), ``times`` (max fires), and ``p`` (per-hit
probability from a privately seeded RNG — deterministic given the
seed, since the scheduler thread is the only caller). The injector
logs every fire in ``fired`` so a chaos bench can print the schedule
it actually executed.

Everything here is stdlib-only at import time (the journal's
standalone loaders must keep working), and the hot-path cost when no
injector is installed is a single attribute test per site — the
FLAGS_serve_journal discipline.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "Clock", "ManualClock", "now", "clock", "set_clock", "use_clock",
    "FaultSpec", "FaultInjector", "InjectedFault", "TokenCorruption",
    "DeadlineExceeded", "ServerOverloaded", "WatchdogTimeout",
    "PoolSizingError", "ReplicaKilled", "FleetOverloaded",
    "TenantQuotaExceeded",
]


# ---------------------------------------------------------------------
# typed serving errors (the failure-semantics vocabulary — see README)
# ---------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """Raised by a scheduled ``raise`` fault at a named site."""

    def __init__(self, site: str, hit: int, message: str = ""):
        super().__init__(
            message or f"injected fault at {site!r} (hit {hit})")
        self.site = site
        self.hit = hit


class TokenCorruption(RuntimeError):
    """Detected out-of-range token out of a decode/prefill program —
    the corrupt-and-DETECT leg: the validator raises this instead of
    letting a poisoned token into a request's stream."""


class DeadlineExceeded(RuntimeError):
    """A request outlived its ``deadline_ms``; surfaced only to that
    request (``req.error``), never to the serve loop."""


class ServerOverloaded(RuntimeError):
    """Typed admission rejection: the inbox is at its bound, the queue
    is past ``FLAGS_serve_shed_queue_depth``, or the SLO burn rate is
    past ``FLAGS_serve_shed_burn_rate``. Raised to the SUBMITTING
    thread — backpressure, not a serve-loop failure."""


class WatchdogTimeout(RuntimeError):
    """A request made no token progress for ``FLAGS_serve_watchdog_steps``
    scheduler steps twice in a row (one preempt/requeue was already
    spent on it)."""


class PoolSizingError(RuntimeError):
    """Configuration error: a request's pages can NEVER fit the pool,
    even with the prefix cache drained and every peer evicted. Not
    retryable — propagates out of ``run()`` with sizing guidance."""


class ReplicaKilled(RuntimeError):
    """A fleet replica's serve loop died — raised by a scheduled
    ``kill`` fault at ``replica.step`` (the simulated process crash)
    or recorded by :meth:`FleetRouter.kill`. The router detects it,
    marks the replica dead, and FAILS OVER its in-flight requests to
    healthy peers (serving/router.py)."""

    def __init__(self, site: str = "replica.step", hit: int = -1,
                 message: str = ""):
        super().__init__(
            message or f"replica killed at {site!r} (hit {hit})")
        self.site = site
        self.hit = hit


class FleetOverloaded(ServerOverloaded):
    """Router-tier overload shedding: the fleet-wide dispatch queue
    (queued-not-yet-admitted requests across every replica) is past
    ``FLAGS_fleet_dispatch_queue``, or no replica is dispatchable
    (every one dead/draining or circuit-open). Raised to the
    SUBMITTING thread BEFORE any replica admits — a subclass of
    :class:`ServerOverloaded` so producers catch both the same way."""


class TenantQuotaExceeded(ServerOverloaded):
    """Router-tier per-tenant quota shedding: the tenant is past its
    ``FLAGS_tenant_quota_rps`` request rate or its
    ``FLAGS_tenant_quota_tokens`` rolling token budget (fed by the
    usage ledger). Raised to the SUBMITTING thread before any replica
    admits — one tenant's burst backpressures that tenant alone. A
    subclass of :class:`ServerOverloaded` so producers catch both the
    same way."""

    def __init__(self, tenant: str, kind: str = "rate",
                 message: str = ""):
        super().__init__(
            message or f"tenant {tenant!r} over its {kind} quota")
        self.tenant = tenant
        self.kind = kind


# ---------------------------------------------------------------------
# the clock seam
# ---------------------------------------------------------------------

class Clock:
    """Injectable monotonic clock: the single time source for serving
    lifecycle marks (arrival/admitted/first-token/done), journal
    timestamps, deadlines, and retry backoff sleeps."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class ManualClock(Clock):
    """Test clock: ``now()`` returns a number you advance; ``sleep``
    advances it (a backoff under ManualClock is a pure time-warp, so
    deadline/watchdog/backoff tests are deterministic and instant)."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> float:
        with self._lock:
            self._t += max(float(seconds), 0.0)
            return self._t


_CLOCK: Clock = Clock()


def clock() -> Clock:
    """The installed serving clock."""
    return _CLOCK


def set_clock(c: Optional[Clock]) -> Clock:
    """Install a clock (None restores the real monotonic clock);
    returns the previously installed one."""
    global _CLOCK
    prev = _CLOCK
    _CLOCK = c if c is not None else Clock()
    return prev


class use_clock:
    """``with use_clock(ManualClock()) as clk: ...`` — scoped install."""

    def __init__(self, c: Clock):
        self._c = c
        self._prev: Optional[Clock] = None

    def __enter__(self) -> Clock:
        self._prev = set_clock(self._c)
        return self._c

    def __exit__(self, *exc) -> None:
        set_clock(self._prev)


def now() -> float:
    """``clock().now()`` — the timestamp every serving/SLO/journal
    mark routes through."""
    return _CLOCK.now()


# ---------------------------------------------------------------------
# fault registry
# ---------------------------------------------------------------------

#: the named-site vocabulary (sites outside it still work — the list
#: documents what the stack wires today)
FAULT_SITES = ("kv.alloc", "kv.grow", "prefill.dispatch",
               "decode.step", "prefix.insert", "journal.dump",
               "router.dispatch", "replica.step", "replica.heartbeat")

_KINDS = ("raise", "delay", "corrupt", "squeeze", "release", "kill",
          "hang")

#: a ``hang`` spec with no explicit delay_ms wedges this long — far
#: past any heartbeat budget, so the health checker always sees the
#: replica miss its beats (a ManualClock turns the wedge into a pure
#: time-warp)
DEFAULT_HANG_MS = 30_000.0


class FaultSpec:
    """One scheduled fault: WHERE (site), WHAT (kind), WHEN (at /
    every / p, capped by times)."""

    __slots__ = ("site", "kind", "at", "every", "times", "p",
                 "delay_ms", "exc", "pages", "value", "fires")

    def __init__(self, site: str, kind: str = "raise", at=None,
                 every: Optional[int] = None, times: int = 1,
                 p: Optional[float] = None, delay_ms: float = 0.0,
                 exc: Optional[BaseException] = None, pages: int = 0,
                 value: Optional[int] = None):
        if kind not in _KINDS:
            raise ValueError(
                f"fault kind {kind!r}: expected one of {_KINDS}")
        if at is None and every is None and p is None:
            at = 0  # default: the site's first hit
        self.site = site
        self.kind = kind
        self.at = ({int(at)} if isinstance(at, int)
                   else None if at is None else {int(x) for x in at})
        self.every = None if every is None else max(int(every), 1)
        self.times = int(times)
        self.p = p
        if kind == "hang" and not delay_ms:
            delay_ms = DEFAULT_HANG_MS
        self.delay_ms = float(delay_ms)
        self.exc = exc
        self.pages = int(pages)
        self.value = value
        self.fires = 0  # fires so far (capped by times)

    def scheduled(self, hit: int, rng: random.Random) -> bool:
        """Does this spec fire on the site's ``hit``-th invocation?
        The rng draw happens for every probed hit of a ``p`` spec, so
        the sequence is deterministic under a fixed seed."""
        if 0 <= self.times <= self.fires:
            return False
        due = False
        if self.at is not None and hit in self.at:
            due = True
        if self.every is not None and (hit + 1) % self.every == 0:
            due = True
        if self.p is not None and rng.random() < self.p:
            due = True
        return due

    def describe(self) -> dict:
        return {"site": self.site, "kind": self.kind,
                "at": sorted(self.at) if self.at else None,
                "every": self.every, "times": self.times, "p": self.p,
                "delay_ms": self.delay_ms, "pages": self.pages}


class FaultInjector:
    """Seeded, scheduled fault registry (see module docstring).

    Usage::

        inj = (FaultInjector(seed=0)
               .add("kv.grow", kind="raise", at=2)
               .add("decode.step", kind="corrupt", at=5)
               .add("decode.step", kind="squeeze", pages=6, at=3)
               .add("decode.step", kind="release", at=9))
        eng = ServingEngine(model, faults=inj)

    Sites call :meth:`fire` once per invocation (raise/delay/squeeze/
    release kinds execute there) and value-producing sites additionally
    route their value through :meth:`corrupt` / :meth:`corrupt_array`
    (corrupt kinds apply to the SAME hit ``fire`` just counted). The
    engine binds its page manager and journal at install so squeezes
    work the real free list and every fire lands on the flight
    recorder's timeline as a ``fault`` event.
    """

    #: out-of-range sentinel a ``corrupt`` spec pokes into a token
    #: stream when no explicit ``value`` is given — far outside any
    #: vocab so range validation always detects it
    CORRUPT_TOKEN = -(1 << 30)

    def __init__(self, specs=(), seed: int = 0):
        self._specs: List[FaultSpec] = []
        self._hits: Dict[str, int] = {}
        self._rng = random.Random(seed)
        self.seed = seed
        #: every executed fault action: {site, hit, kind, ...}
        self.fired: List[dict] = []
        self._mgr = None
        self._journal = None
        self._squeezed: List[Any] = []  # fault-owned page-list keys
        for s in specs:
            if isinstance(s, FaultSpec):
                self._specs.append(s)
            else:  # (site, kind, kwargs) tuples for declarative plans
                site, kind, kw = s
                self._specs.append(FaultSpec(site, kind, **kw))

    # -------------- plan construction --------------

    def add(self, site: str, kind: str = "raise", **kw) -> "FaultInjector":
        self._specs.append(FaultSpec(site, kind, **kw))
        return self

    def bind(self, mgr=None, journal=None) -> "FaultInjector":
        """Attach the live page manager (squeeze target) and flight
        recorder (fault events). The engine calls this at install."""
        if mgr is not None:
            self._mgr = mgr
        if journal is not None:
            self._journal = journal
        return self

    def plan(self) -> List[dict]:
        """The declared schedule (for bench output/logging)."""
        return [s.describe() for s in self._specs]

    def hits(self, site: str) -> int:
        """Invocations seen at ``site`` so far."""
        return self._hits.get(site, 0)

    @property
    def squeezed_pages(self) -> int:
        if self._mgr is None:
            return 0
        return sum(len(self._mgr._owned.get(k, ()))
                   for k in self._squeezed)

    # -------------- site entry points --------------

    def fire(self, site: str, rid: int = -1) -> None:
        """One site invocation: bump the hit counter and execute every
        scheduled raise/delay/squeeze/release spec. ``raise`` specs
        execute LAST so delays/squeezes on the same hit still land."""
        hit = self._hits.get(site, 0)
        self._hits[site] = hit + 1
        to_raise: Optional[BaseException] = None
        for spec in self._specs:
            if spec.site != site or spec.kind == "corrupt":
                continue
            if not spec.scheduled(hit, self._rng):
                continue
            spec.fires += 1
            self._log(site, hit, spec.kind, rid)
            if spec.kind in ("delay", "hang"):
                clock().sleep(spec.delay_ms / 1e3)
            elif spec.kind == "squeeze":
                self._squeeze(spec.pages)
            elif spec.kind == "release":
                self._release_squeezed()
            elif spec.kind == "kill":
                to_raise = spec.exc if spec.exc is not None \
                    else ReplicaKilled(site, hit)
            elif spec.kind == "raise":
                to_raise = spec.exc if spec.exc is not None \
                    else InjectedFault(site, hit)
        if to_raise is not None:
            raise to_raise

    def corrupt(self, site: str, value: int) -> int:
        """Route a site's produced value (token id) through any
        ``corrupt`` spec scheduled for the site's LAST counted hit."""
        hit = self._hits.get(site, 0) - 1
        if hit < 0:
            return value
        for spec in self._specs:
            if spec.site != site or spec.kind != "corrupt":
                continue
            if not spec.scheduled(hit, self._rng):
                continue
            spec.fires += 1
            self._log(site, hit, "corrupt", -1)
            value = self.CORRUPT_TOKEN if spec.value is None \
                else spec.value
        return value

    def corrupt_array(self, site: str, arr) -> None:
        """In-place corruption of a token matrix (decode chunk): poke
        cell [0, 0] — the validator scans the whole array, so where
        the poison lands is immaterial."""
        poked = self.corrupt(site, int(arr.flat[0]) if arr.size else 0)
        if arr.size and poked != int(arr.flat[0]):
            arr.flat[0] = poked

    def release_all(self) -> None:
        """Return every squeezed page to the pool (test teardown)."""
        self._release_squeezed(log=False)

    # -------------- internals --------------

    def _log(self, site: str, hit: int, kind: str, rid: int) -> None:
        entry = {"site": site, "hit": hit, "kind": kind}
        self.fired.append(entry)
        jr = self._journal
        if jr is not None:
            jr.record("fault", rid, -1, dict(entry))
        try:  # lazy + best-effort: the injector must work standalone
            from ..profiler import stats as _stats

            _stats.inc("serving.faults_injected")
        except ImportError:  # standalone import of this file
            pass

    def _squeeze(self, n_pages: int) -> None:
        """Deterministic pool exhaustion: seize up to n free pages
        under a fault-owned key, straight off the free list (never
        through ``allocate`` — the injector must not trip its own
        ``kv.alloc`` site)."""
        mgr = self._mgr
        if mgr is None:
            return
        take = min(int(n_pages), len(mgr._free))
        if take <= 0:
            return
        pages = [mgr._free.pop() for _ in range(take)]
        for p in pages:
            mgr._refs[p] = 1
        key = ("__fault__", len(self._squeezed))
        mgr._owned[key] = pages
        self._squeezed.append(key)

    def _release_squeezed(self, log: bool = True) -> None:
        mgr = self._mgr
        if mgr is None:
            return
        for key in self._squeezed:
            if key in mgr._owned:
                mgr.free(key)
        self._squeezed = []
