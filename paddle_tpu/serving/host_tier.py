"""Host-DRAM KV tier: spill cold pages instead of recomputing them.

HBM holds the hot working set; everything the pool evicts under
pressure — cold ``PrefixCache`` chains, a preempted slot's complete
pages — used to be released outright, turning the next admission into
a full re-prefill (the evict-or-recompute cliff). This tier adds the
memory level in between: evicted pages ``device_get`` into host
buffers keyed by the SAME blake2b content chain the prefix cache uses,
and a later admission restores them with one batched allocate+scatter
(``kv_cache.restore_scatter``, a donated program) instead of burning
prefill FLOPs. int8 cache-KV spills its quantized rows plus the f32
scale-plane columns, so spilled traffic roughly halves vs bf16.

Accounting is page-exact: ``fleet.spills``/``fleet.restores`` count
pages, ``fleet.spill_bytes``/``fleet.restore_bytes`` count measured
host-blob bytes, and ``tier.host_{pages,bytes,capacity_bytes}`` gauges
publish the live occupancy summed over every tier in the process (one
per engine). Over-capacity spills LRU-evict host entries
(``fleet.host_evictions``) — the invariant the accounting tests pin is
``spills - restores - host_evictions - dropped == live entries``.

The router's prefix directory (serving/router.py) subscribes via the
``on_spill``/``on_restore`` callbacks to track which tier each chain
key lives in fleet-wide.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..profiler import stats as _stats

__all__ = ["HostKVTier"]

#: every live tier in the process — the ``tier.*`` gauges publish the
#: fleet-wide sum so serve_top/telemetry see one occupancy number
_TIERS: "weakref.WeakSet" = weakref.WeakSet()


def _publish_gauges() -> None:
    tiers = list(_TIERS)
    _stats.set_gauge("tier.host_pages", sum(len(t) for t in tiers))
    _stats.set_gauge("tier.host_bytes",
                     sum(t.bytes_used for t in tiers))
    _stats.set_gauge("tier.host_capacity_bytes",
                     sum(t.capacity_bytes for t in tiers))


class HostKVTier:
    """LRU host-buffer store of spilled KV pages for ONE engine.

    Entries are per-page host blobs keyed by the prefix-cache chain key
    of the page's token contents — content-addressed, so a restore is
    correct on any admission whose prompt walks the same chain, and a
    preempted slot's pages restore through the ordinary prefix path.
    """

    def __init__(self, eng, capacity_bytes: int, journal=None):
        self._eng = eng
        self._mgr = eng._mgr
        self.capacity_bytes = int(capacity_bytes)
        #: HBM bytes one logical page frees when spilled (the directory
        #: cost model's unit); host blob bytes are measured exactly
        self.page_bytes = self._mgr.page_hbm_bytes()
        self.bytes_used = 0
        self._journal = journal
        self._entries: "OrderedDict[bytes, dict]" = OrderedDict()
        #: router directory subscriptions: called with the chain key
        #: after a page enters (on_spill) / leaves (on_restore) the tier
        self.on_spill: Optional[Callable[[bytes], None]] = None
        self.on_restore: Optional[Callable[[bytes], None]] = None
        self.on_drop: Optional[Callable[[bytes], None]] = None
        self._restore_seq = 0
        _TIERS.add(self)
        _publish_gauges()

    def __len__(self) -> int:
        return len(self._entries)

    def has(self, key: bytes) -> bool:
        return key in self._entries

    @staticmethod
    def _entry_bytes(ent: dict) -> int:
        return sum(int(a.nbytes) for a in ent.values()
                   if isinstance(a, np.ndarray))

    def _evict_lru(self) -> None:
        key, ent = self._entries.popitem(last=False)
        self.bytes_used -= ent["_bytes"]
        _stats.inc("fleet.host_evictions")
        if self.on_drop is not None:
            # gone from the tier entirely — the directory forgets it
            self.on_drop(key)

    # ------------------------------ spill ------------------------------

    def spill(self, key: bytes, page: int) -> int:
        return self.spill_pages([key], [page])

    def spill_pages(self, keys: Sequence[bytes],
                    pages: Sequence[int]) -> int:
        """Copy immutable full pages ``keys[i] -> pages[i]`` to host
        buffers in ONE gather. Pages are NOT released here — the caller
        keeps its reference and releases after, so a failed spill never
        loses KV. Returns the number of pages that landed."""
        todo = [(k, p) for k, p in zip(keys, pages)
                if k not in self._entries]
        for k in keys:
            if k in self._entries:
                self._entries.move_to_end(k)
        if not todo or self.capacity_bytes <= 0:
            return 0
        blob = self._eng.export_kv_pages([p for _, p in todo])
        n = len(todo)
        L = self._mgr.num_layers
        k = blob["k"].reshape(L, n, *blob["k"].shape[1:])
        v = blob["v"].reshape(L, n, *blob["v"].shape[1:])
        if blob["int8"]:
            H = self._mgr._pool_heads
            ps = self._mgr.page_size
            ks = blob["k_scale"].reshape(H, L, n, ps)
            vs = blob["v_scale"].reshape(H, L, n, ps)
        spilled = spilled_bytes = 0
        for j, (key, _page) in enumerate(todo):
            ent = {"k": np.ascontiguousarray(k[:, j]),
                   "v": np.ascontiguousarray(v[:, j])}
            if blob["int8"]:
                ent["int8"] = True
                ent["k_scale"] = np.ascontiguousarray(ks[:, :, j])
                ent["v_scale"] = np.ascontiguousarray(vs[:, :, j])
            nb = self._entry_bytes(ent)
            while self.bytes_used + nb > self.capacity_bytes \
                    and self._entries:
                self._evict_lru()
            if self.bytes_used + nb > self.capacity_bytes:
                break  # tier genuinely too small for one more page
            ent["_bytes"] = nb
            self._entries[key] = ent
            self.bytes_used += nb
            spilled += 1
            spilled_bytes += nb
            if self.on_spill is not None:
                self.on_spill(key)
        if spilled:
            _stats.inc("fleet.spills", spilled)
            _stats.inc("fleet.spill_bytes", spilled_bytes)
            if self._journal is not None:
                self._journal.record("spill", -1, -1,
                                     {"pages": spilled,
                                      "bytes": spilled_bytes})
        _publish_gauges()
        return spilled

    # ----------------------------- restore -----------------------------

    def restore_run(self, keys: Sequence[bytes]) -> Optional[List[int]]:
        """Restore a run of host entries in ONE allocate+scatter:
        allocates ``len(keys)`` pool pages, rebuilds the layer-major
        batch blob, scatters it, and pops the host entries. The pages
        come back with the allocation's single reference TRANSFERRED
        to the caller (the prefix cache registers them as entries).
        None when a key is missing or the pool can't cover."""
        keys = list(keys)
        if not keys:
            return []
        ents = []
        for key in keys:
            ent = self._entries.get(key)
            if ent is None:
                return None
            ents.append(ent)
        m = len(keys)
        if m > self._mgr.free_pages:
            return None
        self._restore_seq += 1
        tmp = ("hostrestore", self._restore_seq)
        pages = self._mgr.allocate(tmp, m * self._mgr.page_size)
        L = self._mgr.num_layers
        batch = {
            "n_pages": m, "int8": bool(ents[0].get("int8")),
            "k": np.stack([e["k"] for e in ents], axis=1).reshape(
                L * m, *ents[0]["k"].shape[1:]),
            "v": np.stack([e["v"] for e in ents], axis=1).reshape(
                L * m, *ents[0]["v"].shape[1:]),
        }
        if batch["int8"]:
            H = self._mgr._pool_heads
            batch["k_scale"] = np.stack(
                [e["k_scale"] for e in ents], axis=2).reshape(H, -1)
            batch["v_scale"] = np.stack(
                [e["v_scale"] for e in ents], axis=2).reshape(H, -1)
        self._eng.import_kv_pages(pages, batch)
        # ownership transfer: the temp key's page list dissolves and
        # the caller inherits the pages' single reference
        self._mgr._owned.pop(tmp, None)
        restored_bytes = 0
        for key, ent in zip(keys, ents):
            del self._entries[key]
            self.bytes_used -= ent["_bytes"]
            restored_bytes += ent["_bytes"]
            if self.on_restore is not None:
                self.on_restore(key)
        _stats.inc("fleet.restores", m)
        _stats.inc("fleet.restore_bytes", restored_bytes)
        if self._journal is not None:
            self._journal.record("restore", -1, -1,
                                 {"pages": m, "bytes": restored_bytes})
        _publish_gauges()
        return pages

    # ------------------------------ admin ------------------------------

    def drop(self, n_entries: int) -> int:
        """Drop up to n LRU entries without restoring (tests/draining)."""
        dropped = 0
        while self._entries and dropped < n_entries:
            self._evict_lru()
            dropped += 1
        _publish_gauges()
        return dropped

    def clear(self) -> int:
        return self.drop(len(self._entries))
