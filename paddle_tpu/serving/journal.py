"""Request-lifecycle flight recorder: bounded ring journal + exporters.

The serving frontend's aggregate counters/histograms (PR 1–2 stats
stack) can tell you that p99 TTFT regressed; they cannot reconstruct
WHY request 17 stalled — it was preempted twice, re-queued behind a
burst, and its resume prefill evicted half the prefix cache. The
flight recorder closes that gap: every lifecycle transition ::

    submit -> queued -> admitted[prefix_pages=k]
           -> prefill_chunk[c,pos]* -> first_token -> decode
           -> {preempt | requeue | stall | evict_trigger}*
           -> finish | error

lands in a bounded in-memory ring as ``(seq, monotonic_ts, event,
request_id, slot, extra)``, written from the scheduler hooks in
``serving/scheduler.py``, ``inference/engine.py`` and
``serving/prefix_cache.py``.

Design constraints:

- **lock-cheap**: ``record`` is one ``itertools.count`` bump (atomic
  under CPython — the GenRequest id-allocation idiom) plus one list
  setitem; no lock is ever taken on the scheduler hot path, and any
  submit-thread race costs at worst one overwritten ring slot.
- **bounded**: the ring holds ``capacity`` events; older events are
  overwritten (``dropped`` counts them) so a week-long serve never
  grows the journal.
- **near-zero when disabled**: the engine holds ``journal = None``
  when ``FLAGS_serve_journal`` is off, so every hook is a single
  attribute test — no event tuples, no extra dicts, nothing.

Exporters: ``dump_jsonl``/``load_jsonl`` (the crash-dump artifact
format, ``tools/serve_top.py``'s offline input) and ``chrome_trace``
— one lane per request with ``pid = process_index``, so
``tools/trace_merge.py`` folds multi-rank serves into one timeline.

This module is deliberately stdlib-only at import time so
``tools/serve_top.py`` can load it standalone for offline post-mortems
without paying the paddle_tpu/jax import.
"""
from __future__ import annotations

import itertools
import json
import os
import time
from typing import List, Optional

try:  # the serving clock seam (serving/faults.py): journal timestamps
    # follow the same injectable monotonic clock as every lifecycle
    # mark, so ManualClock tests see consistent timelines. The
    # fallback keeps this module loadable STANDALONE (tools/serve_top
    # imports it by file path, outside the package).
    from .faults import now as _now
except ImportError:  # standalone load — real monotonic clock
    _now = time.monotonic

__all__ = ["FlightRecorder", "LIFECYCLE_EVENTS", "chrome_trace",
           "load_jsonl"]

#: the journal's event vocabulary, in canonical lifecycle order
#: (ISSUE 11 adds the failure-semantics events: ``fault`` = an
#: injected-fault fire, ``retry`` = a crash-isolated step backoff,
#: ``watchdog`` = a no-progress trip, and the terminal
#: ``deadline_exceeded`` / ``shed``; ISSUE 12 adds ``spec_verify`` —
#: one speculative draft+verify round on a decode slot, with
#: ``k``/``accepted``/``dur_ms`` extras, rendered as a span in the
#: chrome trace and folded into serve_top's accept-rate row; ISSUE 14
#: adds the fleet-tier events — ``failover`` = a dead replica's
#: request re-dispatched to this replica (extras ``from``/``to``/
#: ``n_generated``), ``migrate`` = a mid-decode request's KV pages
#: handed to this replica during a graceful drain (``from``/``to``/
#: ``pages``), ``drain`` = this replica entering/finishing its drain;
#: each lands in the DESTINATION (failover/migrate) or draining
#: replica's journal, and replica journals export with pid = replica
#: id so tools/trace_merge.py folds a fleet serve into one timeline;
#: ISSUE 16 adds ``alert`` — a telemetry alert-rule transition
#: (extras ``name``/``metric``/``state`` firing|resolved/``value``/
#: ``threshold``, from profiler/alerts.py), rid/slot = -1 since an
#: alert belongs to the serve, not one request)
LIFECYCLE_EVENTS = (
    "submit", "queued", "admitted", "prefill_chunk", "first_token",
    "decode", "spec_verify", "preempt", "requeue", "stall",
    "evict_trigger", "fault", "retry", "watchdog",
    "failover", "migrate", "handoff", "spill", "restore",
    "drain", "alert",
    "finish", "error", "deadline_exceeded", "shed",
)


class FlightRecorder:
    """Bounded ring-buffer journal of request-lifecycle events."""

    __slots__ = ("capacity", "_ring", "_ctr")

    def __init__(self, capacity: int = 4096):
        self.capacity = max(int(capacity), 1)
        self._ring: list = [None] * self.capacity
        self._ctr = itertools.count()

    # ---------------- recording (hot path) ----------------

    def record(self, ev: str, rid: int = -1, slot: int = -1,
               extra: Optional[dict] = None) -> None:
        """Append one event. ``rid=-1`` marks engine-level events
        (pool eviction, crash); ``extra`` is a small dict of fields
        (page counts, chunk position, ttft) or None."""
        i = next(self._ctr)
        self._ring[i % self.capacity] = (
            i, _now(), ev, rid, slot, extra)

    # ---------------- reading ----------------

    @property
    def recorded(self) -> int:
        """Events ever recorded (including overwritten ones)."""
        seqs = [e[0] for e in self._ring if e is not None]
        return (max(seqs) + 1) if seqs else 0

    @property
    def dropped(self) -> int:
        """Events overwritten by ring wrap-around."""
        return max(0, self.recorded - self.capacity)

    def events(self, rid: Optional[int] = None) -> List[dict]:
        """Surviving events in recording order, as flat dicts
        (``seq``/``ts``/``ev``/``rid``/``slot`` + any extra fields),
        optionally filtered to one request's lane."""
        out = []
        for entry in sorted(e for e in self._ring if e is not None):
            seq, ts, ev, r, slot, extra = entry
            if rid is not None and r != rid:
                continue
            d = {"seq": seq, "ts": round(ts, 6), "ev": ev, "rid": r,
                 "slot": slot}
            if extra:
                d.update(extra)
            out.append(d)
        return out

    def tail(self, n: int) -> List[dict]:
        """The last ``n`` surviving events (crash-dump view)."""
        return self.events()[-max(int(n), 0):]

    def clear(self) -> None:
        """Drop every event and restart the sequence (bench warmup)."""
        self._ring = [None] * self.capacity
        self._ctr = itertools.count()

    # ---------------- exporters ----------------

    def dump_jsonl(self, path: str) -> str:
        """Write the surviving events as ``{"type": "event", ...}``
        JSONL lines (the ``tools/serve_top.py`` offline format). The
        target directory is created if missing — a journal dump is
        usually the LAST thing a dying serve does, and must not fail
        on a fresh artifact directory."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for d in self.events():
                f.write(json.dumps({"type": "event", **d}) + "\n")
        return path

    def publish_gauges(self) -> None:
        """Publish ``journal.{events,dropped}`` gauges to the stats
        registry (called at run()/bench exit, not per event — the
        ring itself never touches a metric lock)."""
        from paddle_tpu.profiler import stats as _stats

        _stats.set_gauge("journal.events", self.recorded)
        _stats.set_gauge("journal.dropped", self.dropped)


def load_jsonl(path: str):
    """Parse a journal / crash-dump JSONL artifact.

    Returns ``(events, extras)``: the ``type=event`` lines in sequence
    order, and every other line (``stats`` snapshot, ``crash`` header)
    keyed by its type."""
    events: List[dict] = []
    extras: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            t = d.pop("type", "event")
            if t == "event":
                events.append(d)
            else:
                extras[t] = d
    events.sort(key=lambda d: d.get("seq", 0))
    return events, extras


#: lifecycle transitions that OPEN a phase span on a request's lane
#: (``failover`` re-queues the request on the surviving replica's
#: lane; ``migrate``/``handoff`` land it straight in decode — no
#: prefill replay. ``spill``/``restore`` are engine-level rid=-1
#: instants: host-tier page traffic, not a request phase)
_PHASE_OF = {"submit": "queued", "queued": "queued",
             "admitted": "prefill", "decode": "decode",
             "failover": "queued", "migrate": "decode",
             "handoff": "decode"}
#: transitions that CLOSE whatever phase is open
_CLOSERS = ("preempt", "requeue", "finish", "error",
            "deadline_exceeded", "shed")


def chrome_trace(events: List[dict], process_index: int = 0) -> dict:
    """Chrome-trace view of a journal: ONE LANE PER REQUEST.

    ``pid = process_index`` (the producing rank) and ``metadata``
    carries the same stamp, so ``tools/trace_merge.py`` folds
    multi-rank serve journals into one fleet timeline exactly like
    profiler traces. Each request renders as ``tid = rid + 1`` (lane
    0 is the engine: pool evictions, crash events) with:

    - ``"X"`` phase spans — ``queued`` / ``prefill`` / ``decode`` —
      delimited by the lifecycle transitions (a preempted request
      shows decode → queued → prefill → decode across its lane);
    - ``"i"`` instant marks for every journal event, carrying its
      extra fields (chunk position, prefix pages, ttft) as args.
    """
    pid = int(process_index)
    out: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": f"rank {pid} serve"}},
        {"name": "process_sort_index", "ph": "M", "pid": pid, "tid": 0,
         "args": {"sort_index": pid}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "engine"}},
    ]
    by_rid: dict = {}
    for e in events:
        by_rid.setdefault(int(e.get("rid", -1)), []).append(e)
    for rid in sorted(by_rid):
        evs = sorted(by_rid[rid], key=lambda d: d.get("seq", 0))
        tid = rid + 1 if rid >= 0 else 0
        if rid >= 0:
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": f"req {rid}"}})
            out.append({"name": "thread_sort_index", "ph": "M",
                        "pid": pid, "tid": tid,
                        "args": {"sort_index": tid}})
        open_name = None
        t0 = 0.0
        last_ts = None
        for e in evs:
            ts = float(e["ts"]) * 1e6  # chrome trace wants µs
            last_ts = ts
            ev = e["ev"]
            phase = _PHASE_OF.get(ev)
            if rid >= 0 and phase is not None:
                if open_name != phase:
                    if open_name is not None:
                        out.append({"name": open_name, "ph": "X",
                                    "pid": pid, "tid": tid, "ts": t0,
                                    "dur": max(ts - t0, 0.0),
                                    "cat": "serve",
                                    "args": {"rid": rid}})
                    open_name, t0 = phase, ts
            elif rid >= 0 and ev in _CLOSERS and open_name is not None:
                out.append({"name": open_name, "ph": "X", "pid": pid,
                            "tid": tid, "ts": t0,
                            "dur": max(ts - t0, 0.0), "cat": "serve",
                            "args": {"rid": rid}})
                open_name = None
            args = {k: v for k, v in e.items()
                    if k not in ("seq", "ts", "ev", "rid", "slot")}
            args["rid"] = rid
            if ev == "spec_verify" and "dur_ms" in args:
                # the verify round is journaled at COMPLETION with its
                # wall time — render a proper duration span ending at
                # ts instead of an instant mark
                dur = max(float(args["dur_ms"]) * 1e3, 0.0)
                out.append({"name": "spec_verify", "ph": "X",
                            "pid": pid, "tid": tid, "ts": ts - dur,
                            "dur": dur, "cat": "serve", "args": args})
                continue
            out.append({"name": ev, "ph": "i", "pid": pid, "tid": tid,
                        "ts": ts, "s": "t", "cat": "serve",
                        "args": args})
        if open_name is not None and last_ts is not None:
            # phase still open at journal end (live dump mid-serve)
            out.append({"name": open_name, "ph": "X", "pid": pid,
                        "tid": tid, "ts": t0,
                        "dur": max(last_ts - t0, 0.0), "cat": "serve",
                        "args": {"rid": rid}})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "metadata": {"process_index": pid,
                         "source": "paddle_tpu.serving.journal"}}
