"""Prefix/KV-cache reuse: requests sharing a prompt prefix share pages.

Serving traffic is dominated by a handful of system prompts; with the
paged pool, reusing their KV is a PAGE-TABLE operation, not a copy
(reference comparator: the block-table indirection of
block_multi_head_attention_kernel.cu — vLLM-style automatic prefix
caching on top of it). Every FULL page of a finished prefill registers
here under the hash CHAIN of its token contents (page p's key folds
page p-1's key, so a match certifies the whole prefix, not one page);
admission maps the longest matching chain into the new sequence via
``BlockKVCacheManager.share`` (+1 refcount per page) and chunk-prefills
only the uncovered suffix.

Correctness rests on two invariants:

- causal KV: page p's K/V depend only on tokens ``0 .. (p+1)*ps-1`` —
  exactly the chain content its key hashes — so equal chains mean
  byte-identical KV;
- copy-on-write sharing: only FULL, immutable prompt pages are ever
  registered; a sharer's decode writes land in its privately owned
  tail pages, and the refcount keeps a shared page alive until its
  LAST user frees (see kv_cache.py).

The cache itself holds one reference per registered page, so prefixes
outlive their original request; ``evict`` drops LRU entries under pool
pressure (releasing a mid-chain page strands the chain's tail until
LRU collects it too — harmless, just unreachable).
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional

import numpy as np

__all__ = ["PrefixCache"]


def _page_key(prev_key: bytes, tokens: np.ndarray) -> bytes:
    """Chain hash of one page's token contents (content-addressed, so
    hash collisions — not python hash(), which is per-process salted —
    would alias DIFFERENT prompts onto one page's KV; blake2b-128
    makes that astronomically unlikely)."""
    h = hashlib.blake2b(prev_key, digest_size=16)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


class PrefixCache:
    """Hash-chain lookup from prompt prefixes to live pool pages."""

    def __init__(self, mgr, page_size: int,
                 capacity_pages: Optional[int] = None, journal=None):
        self._mgr = mgr
        self.page_size = int(page_size)
        #: max registered pages (None = bounded only by pool pressure
        #: via ``evict``); exceeding it LRU-evicts before insert
        self.capacity_pages = capacity_pages
        #: serving flight recorder (serving/journal.py) or None —
        #: evictions are the pool-pressure signal a post-mortem needs
        #: next to the preempt/requeue events they interleave with
        self._journal = journal
        #: fault-injection registry (serving/faults.py) or None; the
        #: ``prefix.insert`` site fires at the TOP of insert, before
        #: any page ref is taken, so an injected failure never leaks
        #: a retain
        self._faults = None
        #: host-DRAM KV tier (serving/host_tier.py) or None — when set,
        #: every eviction funnels through the spill decision point and
        #: ``restore_chain`` pulls spilled continuations back before
        #: admission re-prefills them
        self.host_tier = None
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def _chain(self, prompt, n_pages: int):
        ps = self.page_size
        key = b""
        for p in range(n_pages):
            key = _page_key(key, prompt[p * ps: (p + 1) * ps])
            yield key

    def match(self, prompt) -> List[int]:
        """Longest cached chain of pages covering ``prompt``'s leading
        tokens, LRU-touched. Capped at ``(len-1)//page_size`` pages so
        at least the final prompt token always prefills — the first
        emitted token needs its freshly computed hidden state. Pure
        lookup: the scheduler owns the hit/miss counters (a request is
        one hit, however many times admission probes it)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_pages = max(0, (len(prompt) - 1) // self.page_size)
        pages: List[int] = []
        for key in self._chain(prompt, max_pages):
            page = self._entries.get(key)
            if page is None:
                break
            self._entries.move_to_end(key)
            pages.append(page)
        return pages

    def insert(self, prompt, pages) -> int:
        """Register a FULLY PREFILLED prompt's full pages (``pages[p]``
        holds tokens ``p*ps..(p+1)*ps-1``; the trailing partial page is
        never registered). Already-cached chain segments dedupe to an
        LRU touch. Returns the number of newly registered pages."""
        f = self._faults
        if f is not None:
            f.fire("prefix.insert")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n_full = min(len(pages), len(prompt) // self.page_size)
        added = 0
        for p, key in enumerate(self._chain(prompt, n_full)):
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            if self.capacity_pages is not None:
                while len(self._entries) >= self.capacity_pages:
                    if not self.evict(1):
                        return added
            self._mgr.retain([pages[p]])
            self._entries[key] = pages[p]
            added += 1
        return added

    def evict(self, n_entries: int) -> int:
        """Drop up to n LRU entries, releasing the cache's reference
        (a page whose LAST reference this was returns to the free
        list; one still mapped by a live sequence just drops to its
        sharers). Admission calls this under pool pressure. EVERY
        eviction routes through the spill decision point below, so a
        configured host tier turns pool pressure into a demotion
        instead of a recompute — with no tier the decision degrades to
        the plain release this always was."""
        dropped = spilled = 0
        while self._entries and dropped < n_entries:
            key, page = self._entries.popitem(last=False)
            spilled += self._spill_or_release(key, page)
            dropped += 1
        if dropped and self._journal is not None:
            self._journal.record("evict_trigger", -1, -1,
                                 {"pages": dropped, "spilled": spilled})
        return dropped

    def _spill_or_release(self, key: bytes, page: int) -> int:
        """The single evict-vs-spill decision point (ISSUE 20): copy
        the page's KV to the host tier (content-keyed, so any later
        prompt walking the same chain can restore it), THEN drop the
        cache's reference. The spill happens before the release, so a
        tier rejection (over capacity, tier disabled) leaves exactly
        the old eviction behaviour. Returns 1 if the page spilled."""
        ht = self.host_tier
        spilled = ht.spill(key, page) if ht is not None else 0
        self._mgr.release_pages([page])
        return spilled

    def restore_chain(self, prompt, reserve: int = 1) -> int:
        """Pull ``prompt``'s spilled chain continuation back from the
        host tier into free pool pages — called once per admission
        probe BEFORE ``match``, so restored pages are indistinguishable
        from never-evicted ones. Walks the chain past the cached
        prefix, batches every consecutive host-resident key into one
        allocate+scatter, and registers the pages as ordinary entries.
        ``reserve`` pool pages are left free for the admission's own
        first chunk so a restore can never starve the very request it
        serves. Returns the number of pages restored."""
        ht = self.host_tier
        if ht is None or not len(ht):
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_pages = max(0, (len(prompt) - 1) // self.page_size)
        budget = self._mgr.free_pages - max(int(reserve), 0)
        if self.capacity_pages is not None:
            budget = min(budget,
                         self.capacity_pages - len(self._entries))
        if budget <= 0:
            return 0
        to_restore: List[bytes] = []
        for key in self._chain(prompt, max_pages):
            if key in self._entries:
                continue
            if not ht.has(key) or len(to_restore) >= budget:
                break
            to_restore.append(key)
        if not to_restore:
            return 0
        pages = ht.restore_run(to_restore)
        if pages is None:
            return 0
        for key, page in zip(to_restore, pages):
            # the restore's single page reference transfers to the
            # cache entry — same ownership shape as a fresh insert
            self._entries[key] = page
        return len(pages)

    def clear(self) -> int:
        return self.evict(len(self._entries))
