"""Serving request: the admission unit of the SLO-aware frontend.

Extends the continuous-batching :class:`GenRequest` with what a real
service needs per request: an arrival timestamp (Poisson load, queue-
wait accounting), a priority (admission ordering), a streaming token
callback (tokens reach the caller as they decode, not at drain), the
SLO lifecycle marks (admitted / first token / done) the scheduler
stamps so TTFT/TPOT are measured per request, not per batch, and the
failure-semantics surface (ISSUE 11): an optional per-request
``deadline_ms``, a TERMINAL ``state``, and the ``error`` that ended a
request that didn't finish cleanly.

Every timestamp routes through the injectable serving clock
(``serving/faults.py``), so deadline/TTFT behavior is deterministic
under a ``ManualClock``.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..inference.engine import GenRequest
from . import faults as _faults

__all__ = ["Request"]


class Request(GenRequest):
    """One request through the serving frontend.

    ``priority``: higher admits first (FIFO within a priority level;
    the admission skip-ahead's starvation bound still applies).
    ``on_token(req, token)``: called on the scheduler thread for every
    generated token, including the first one emitted by the final
    prefill chunk — the streaming surface.
    ``arrival_time``: serving-clock time at construction unless the
    caller replays recorded traffic with its own timestamps.
    ``deadline_ms``: wall budget from ARRIVAL; once exceeded the
    scheduler aborts the request wherever it is (queue, prefill slot,
    decode slot), frees its pages, and surfaces
    :class:`~paddle_tpu.serving.faults.DeadlineExceeded` only to this
    request (``state == "deadline_exceeded"``, ``error`` set).

    Terminal ``state`` values: ``"ok"`` (finished cleanly),
    ``"error"`` (step failure after retries, watchdog kill),
    ``"deadline_exceeded"``, ``"shed"`` (overload rejection at drain);
    None while in flight.
    """

    def __init__(self, prompt, max_new_tokens: int = 32,
                 eos_token_id=None, priority: int = 0,
                 on_token: Optional[Callable] = None,
                 arrival_time: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 tenant: Optional[str] = None,
                 adapter_id: Optional[str] = None):
        super().__init__(prompt, max_new_tokens, eos_token_id)
        self.priority = int(priority)
        self.on_token = on_token
        # usage-metering identity (ISSUE 17): None bills to the
        # ledger's default tenant; stamped into journal events and
        # the per-request usage record
        self.tenant = tenant
        # batched multi-LoRA (ISSUE 18): name of the AdapterBank
        # entry this request decodes through (None = base model).
        # The scheduler acquires the adapter at submit — pinning it
        # against unload — and stamps the resolved bank slot here;
        # the slot rides preempt/resume and fleet re-dispatch (each
        # engine re-resolves against its own bank at adoption).
        self.adapter_id = adapter_id
        self._adapter_slot: Optional[int] = None
        self.arrival_time = _faults.now() if arrival_time is None \
            else float(arrival_time)
        self.deadline_ms = None if deadline_ms is None \
            else float(deadline_ms)
        # SLO lifecycle marks (serving-clock seconds), stamped by the
        # scheduler: admission, first emitted token, completion
        self.t_admitted: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        # pool-pressure lifecycle counts (scheduler-stamped) + the
        # SLO verdict (serving/slo.py, stamped at finish) — the
        # per-request JSONL serve_bench emits reads these directly
        self.n_preempts = 0
        self.n_requeues = 0
        self.slo_ok: Optional[bool] = None
        # failure semantics (ISSUE 11): terminal state + the error
        # that ended a request that didn't finish cleanly, and the
        # crash-isolation retry/watchdog bookkeeping
        self.state: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.n_retries = 0
        self._wd_mark = None          # (phase, progress) watchdog mark
        self._wd_steps = 0            # steps since the mark moved
        self._wd_trips = 0            # watchdog firings (2nd = fatal)

    # ---- derived SLO readings (None until the mark exists) ----

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admitted is None:
            return None
        return self.t_admitted - self.arrival_time

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, from ARRIVAL (queue wait included —
        the number the user experiences, not the scheduler's)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.arrival_time

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token after the first."""
        if self.t_done is None or self.t_first_token is None \
                or len(self.generated) < 2:
            return None
        return (self.t_done - self.t_first_token) \
            / (len(self.generated) - 1)

    # ---- failure semantics ----

    def past_deadline(self, now: Optional[float] = None) -> bool:
        """Has this request's deadline budget elapsed (False when no
        deadline is set)?"""
        if self.deadline_ms is None:
            return False
        if now is None:
            now = _faults.now()
        return (now - self.arrival_time) * 1e3 > self.deadline_ms
