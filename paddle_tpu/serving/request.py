"""Serving request: the admission unit of the SLO-aware frontend.

Extends the continuous-batching :class:`GenRequest` with what a real
service needs per request: an arrival timestamp (Poisson load, queue-
wait accounting), a priority (admission ordering), a streaming token
callback (tokens reach the caller as they decode, not at drain), and
the SLO lifecycle marks (admitted / first token / done) the scheduler
stamps so TTFT/TPOT are measured per request, not per batch.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from ..inference.engine import GenRequest

__all__ = ["Request"]


class Request(GenRequest):
    """One request through the serving frontend.

    ``priority``: higher admits first (FIFO within a priority level;
    the admission skip-ahead's starvation bound still applies).
    ``on_token(req, token)``: called on the scheduler thread for every
    generated token, including the first one emitted by the final
    prefill chunk — the streaming surface.
    ``arrival_time``: ``time.monotonic()`` at construction unless the
    caller replays recorded traffic with its own timestamps.
    """

    def __init__(self, prompt, max_new_tokens: int = 32,
                 eos_token_id=None, priority: int = 0,
                 on_token: Optional[Callable] = None,
                 arrival_time: Optional[float] = None):
        super().__init__(prompt, max_new_tokens, eos_token_id)
        self.priority = int(priority)
        self.on_token = on_token
        self.arrival_time = time.monotonic() if arrival_time is None \
            else float(arrival_time)
        # SLO lifecycle marks (monotonic seconds), stamped by the
        # scheduler: admission, first emitted token, completion
        self.t_admitted: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        # pool-pressure lifecycle counts (scheduler-stamped) + the
        # SLO verdict (serving/slo.py, stamped at finish) — the
        # per-request JSONL serve_bench emits reads these directly
        self.n_preempts = 0
        self.n_requeues = 0
        self.slo_ok: Optional[bool] = None

    # ---- derived SLO readings (None until the mark exists) ----

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admitted is None:
            return None
        return self.t_admitted - self.arrival_time

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, from ARRIVAL (queue wait included —
        the number the user experiences, not the scheduler's)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.arrival_time

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token after the first."""
        if self.t_done is None or self.t_first_token is None \
                or len(self.generated) < 2:
            return None
        return (self.t_done - self.t_first_token) \
            / (len(self.generated) - 1)
