"""Fleet router: a health-checked front tier over N serving replicas.

PR 10 scaled one engine UP (tensor parallelism); this scales OUT
(ROADMAP item 2): a :class:`FleetRouter` owns N ``ServingEngine``
replicas (threads with their own engines on CPU; each replica may
itself be a TP group) and is exactly where the fleet's robustness
lives — a single replica crash without it loses every in-flight
request with no detection, no retry, no redirect.

Dispatch — ``policy="affinity"`` (default):

- **prefix affinity**: the router keys each prompt's leading FULL
  pages by the same blake2b hash CHAIN the per-replica
  ``PrefixCache`` uses, and remembers which replica last served each
  chain. A request sharing a system prompt routes to the replica that
  already OWNS those pages, so the fleet-wide hit rate approaches the
  single-replica one instead of dividing by N (the routed >
  round-robin goodput pin under a skewed-prefix Poisson load).
- **load/SLO tie-break**: no chain match → the replica with the
  shallowest queue (inbox + waiting + prefilling + decoding), ties
  broken toward the best rolling ``slo.goodput`` gauge (PR 9).
- ``policy="rr"`` is the round-robin baseline the affinity policy is
  benched against (``serve_bench --fleet --fleet-policy rr``).

Health — every replica's serve loop stamps a HEARTBEAT through the
PR 11 clock seam once per iteration; the router's health checker
walks a missed-beat state machine::

    alive --(>= FLAGS_fleet_suspect_beats missed)--> suspect
          --(>= 2x missed, or a crashed loop)------> dead

- **suspect**: new dispatch avoids the replica, and requests still
  parked in its admission inbox HEDGE to a healthy peer
  (``fleet.hedges``) — they have no KV state yet, so re-dispatch is
  free and nobody queues behind a maybe-dead replica.
- **dead**: crash FAILOVER — every in-flight request (queued,
  prefilling, decoding) re-dispatches to a healthy replica through
  the existing preemption-by-recompute resume path: prompt +
  generated tokens replay (prefix-cache-hot on the survivor) and the
  greedy stream continues byte-identically, so killing 1 of N
  replicas mid-load loses ZERO admitted requests.
- recovered beats walk a suspect replica back to alive.

A per-replica CIRCUIT BREAKER trips after
``FLAGS_fleet_breaker_threshold`` consecutive dispatch errors (the
router stops routing there), then HALF-OPENS after a cooldown: one
probe dispatch re-closes it on success or re-opens it on failure.

Graceful DRAIN (``drain(idx)``) empties a replica WITHOUT recompute:
queued/prefilling requests re-dispatch (no KV worth moving), but each
mid-decode slot's KV pages migrate by PAGE-GRANULAR handoff — a
gather of the slot's pages out of the source pool, a put into freshly
allocated pages on the destination, and a page-table re-home
(``export_slot``/``import_slot``, inference/engine.py). The paged
layout makes this a copy of exactly the live pages; subsequent tokens
are byte-identical because the cached KV and the (factory-replicated)
weights are. Pools that can't hand pages across (int8 cache-KV, TP
kv-head sharding) fall back to the recompute path automatically.

Overload sheds at the ROUTER tier: once the fleet-wide dispatch queue
(every replica's queued-but-unadmitted requests) passes
``FLAGS_fleet_dispatch_queue`` — or no replica is dispatchable — new
submits raise the typed :class:`FleetOverloaded` BEFORE any replica
admits.

Everything is drivable deterministically: the seeded
``serving/faults.py`` registry gains ``router.dispatch`` /
``replica.step`` / ``replica.heartbeat`` sites and ``kill``/``hang``
kinds, and synchronous stepping (``step()``/``run()``) plus the
``ManualClock`` make every transition a unit test
(tests/test_fleet_router.py). ``tools/serve_bench.py --fleet N``
drives the threaded form under Poisson load;
``tools/serve_top.py --fleet`` renders per-replica health rows; each
replica's journal exports with ``pid = replica id`` so
``tools/trace_merge.py`` folds a fleet serve into one timeline.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from collections import deque

from ..core.flags import flag as _flag
from ..profiler import stats as _stats
from . import faults as _faults
from .accounting import UsageLedger, fold_records, tenant_rollup
from .faults import FleetOverloaded, ReplicaKilled, TenantQuotaExceeded
from .prefix_cache import _page_key
from .request import Request
from .scheduler import ServingEngine

__all__ = ["FleetRouter", "Replica", "CircuitBreaker",
           "FleetOverloaded", "ReplicaKilled", "REPLICA_STATES"]

#: replica lifecycle (serve-loop + health-checker state machine)
REPLICA_STATES = ("alive", "suspect", "dead", "draining", "drained")

#: failovers one request may survive before the router fails it
#: terminally — a poison-pill request (e.g. one whose pages can never
#: fit) must not cascade a crash across the whole fleet
MAX_FAILOVERS = 3


class CircuitBreaker:
    """Per-replica dispatch circuit breaker (closed → open →
    half-open), on the injectable serving clock.

    ``record_failure`` after ``FLAGS_fleet_breaker_threshold``
    CONSECUTIVE dispatch errors opens the breaker; ``allow()`` then
    rejects until ``cooldown_ms`` elapses, after which it half-opens
    and each ``allow()`` is a probe — the next outcome re-closes
    (success) or re-opens (failure) it."""

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_ms: float = 250.0):
        self._threshold = threshold
        self.cooldown_ms = float(cooldown_ms)
        self.state = "closed"
        self.failures = 0          # consecutive
        self.trips = 0
        self._opened_at = 0.0

    @property
    def threshold(self) -> int:
        return self._threshold if self._threshold is not None \
            else int(_flag("fleet_breaker_threshold"))

    def allow(self) -> bool:
        if self.state == "open":
            if (_faults.now() - self._opened_at) * 1e3 \
                    >= self.cooldown_ms:
                self.state = "half_open"
        return self.state != "open"

    def record_success(self) -> None:
        self.failures = 0
        self.state = "closed"

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == "half_open" or \
                (self.state == "closed"
                 and self.failures >= self.threshold):
            self.state = "open"
            self.trips += 1
            self._opened_at = _faults.now()


def _parse_disagg(spec, n: int) -> Optional[Tuple[int, int]]:
    """Normalize a disaggregation spec to ``(n_prefill, n_decode)``.

    ``''``/None/False → None (symmetric fleet); ``'auto'``/True →
    half the fleet (at least 1) prefill-heavy, the rest decode-heavy
    — or None when the fleet is too small to split; ``'P:D'`` pins
    the split explicitly (must cover the whole fleet)."""
    if spec is None or spec is False or spec == "":
        return None
    if spec is True or spec == "auto":
        if n < 2:
            return None
        n_pre = max(1, n // 2)
        return (n_pre, n - n_pre)
    s = str(spec)
    if ":" in s:
        p, d = (int(x) for x in s.split(":", 1))
        if p < 1 or d < 1 or p + d != n:
            raise ValueError(
                f"disagg={s!r}: need P>=1, D>=1 and P+D == "
                f"{n} replicas")
        return (p, d)
    raise ValueError(f"disagg={spec!r}: expected '', 'auto' or 'P:D'")


class Replica:
    """One fleet replica: a ``ServingEngine`` plus its serve-loop /
    health / breaker state. ``step_once()`` is the unit both the
    per-replica thread and the router's synchronous ``step()`` drive;
    any exception escaping the engine's (already crash-isolated)
    scheduler step is a REPLICA-LEVEL crash — the loop stops beating
    and the health checker fails its requests over."""

    def __init__(self, idx: int, eng: ServingEngine,
                 router: "FleetRouter",
                 breaker_cooldown_ms: float = 250.0):
        self.idx = idx
        self.eng = eng
        self.router = router
        #: disaggregation role (ISSUE 20): "prefill" | "decode" |
        #: None (symmetric fleet) — stamped by the router
        self.role: Optional[str] = None
        self.state = "alive"
        self.last_beat = _faults.now()
        self.crashed: Optional[BaseException] = None
        self.breaker = CircuitBreaker(cooldown_ms=breaker_cooldown_ms)
        self.thread: Optional[threading.Thread] = None
        #: serializes engine steps against cross-replica mutation
        #: (page import during a drain migration)
        self.step_lock = threading.Lock()

    # ---------------- serve loop ----------------

    @property
    def dead(self) -> bool:
        return self.state in ("dead", "drained")

    def beat(self) -> None:
        """Stamp a heartbeat through the serving clock. A scheduled
        ``replica.heartbeat`` fault SUPPRESSES the stamp — the health
        checker then sees missed beats without the replica dying."""
        fi = self.router.faults
        if fi is not None:
            try:
                fi.fire("replica.heartbeat", rid=self.idx)
            except BaseException:
                return
        self.last_beat = _faults.now()

    def step_once(self) -> bool:
        """One serve-loop iteration: fire the ``replica.step`` fault
        site (kill/hang land here), run one scheduler step when there
        is work, stamp a beat. Returns whether work was done; a crash
        is recorded in ``crashed`` (the loop never raises)."""
        if self.dead or self.crashed is not None:
            return False
        did = False
        try:
            with self.step_lock:
                if self.eng.has_work:
                    fi = self.router.faults
                    if fi is not None:
                        fi.fire("replica.step", rid=self.idx)
                    from ..profiler import RecordEvent
                    with RecordEvent("fleet.replica.step"):
                        self.eng.step()
                    did = True
        except BaseException as e:
            # the scheduler step is already crash-isolated per
            # request; anything that still escapes (an injected
            # kill/raise, PoolSizingError, a wedged runtime) is a
            # replica-level crash: stop beating, let the health
            # checker fail our requests over
            self.crashed = e
            return False
        self.beat()
        return did

    def _loop(self) -> None:
        """Thread body (threaded mode): step until stopped, dead, or
        drained; a ``draining`` state hands the thread to the
        router's migration path so no step races the page export."""
        while not self.router._stop and not self.dead \
                and self.crashed is None:
            if self.state == "draining":
                self.router._drain_now(self)
                return
            if not self.step_once():
                time.sleep(0.0005)
            elif self.role == "prefill":
                # disaggregated fleet: this thread owns the replica's
                # stepping, so the handoff never races its own decode
                self.router._handoff_ready(self)


class FleetRouter:
    """Front tier over N serving replicas (see module docstring).

    Usage::

        router = FleetRouter(engine_factory=lambda i: make_engine(),
                             n_replicas=2)
        router.submit([1, 2, 3], max_new_tokens=16)   # routed
        router.run()                 # synchronous drain (tests), or
        router.start(); ...; router.stop()   # one thread per replica

    ``engine_factory(i)`` must build IDENTICAL engines (same seed →
    same weights): failover replays a request's tokens on a peer and
    migration hands its KV pages across, both of which are
    byte-exact only because every replica computes the same function.
    Pre-built engines can be passed via ``engines=`` instead.
    """

    def __init__(self, engines: Optional[Sequence[ServingEngine]] = None,
                 *, engine_factory: Optional[Callable[[int],
                                                      ServingEngine]] = None,
                 n_replicas: Optional[int] = None,
                 policy: str = "affinity", faults=None,
                 affinity_pages: int = 8,
                 breaker_cooldown_ms: float = 250.0,
                 disagg=None):
        if policy not in ("affinity", "rr"):
            raise ValueError(
                f"policy={policy!r}: expected 'affinity' or 'rr'")
        if engines is None:
            if engine_factory is None or not n_replicas:
                raise ValueError("pass engines= or engine_factory= "
                                 "with n_replicas=")
            engines = [engine_factory(i) for i in range(n_replicas)]
        engines = list(engines)
        if not engines:
            raise ValueError("a fleet needs at least one replica")
        ps = {e.page_size for e in engines}
        if len(ps) != 1:
            raise ValueError(
                f"replicas disagree on page_size ({sorted(ps)}) — "
                "affinity chains and page migration need one layout")
        self.page_size = ps.pop()
        self.policy = policy
        self.affinity_pages = max(int(affinity_pages), 1)
        self.replicas: List[Replica] = [
            Replica(i, e, self, breaker_cooldown_ms)
            for i, e in enumerate(engines)]
        #: fleet-wide prefix DIRECTORY (ISSUE 20): blake2b chain key →
        #: ``(replica idx, tier)`` where tier is ``"hbm"`` (the pages
        #: live in that replica's pool / prefix cache) or ``"host"``
        #: (spilled to its host-DRAM tier). Generalizes the PR 14
        #: chain→replica affinity map; the ``_affinity`` property
        #: keeps the old owner-only read view.
        self._directory: Dict[bytes, Tuple[int, str]] = {}
        # ------ disaggregated prefill/decode roles (ISSUE 20) ------
        self.disagg = _parse_disagg(
            disagg if disagg is not None else _flag("disagg"),
            len(self.replicas))
        if self.disagg is not None:
            n_pre, _ = self.disagg
            for rep in self.replicas:
                rep.role = "prefill" if rep.idx < n_pre else "decode"
                # the scheduler's SLO interleave weights ARE the role:
                # a prefill replica runs long prefill bursts between
                # single decode chunks (its decode slots hand off
                # anyway), a decode replica the inverse
                slo = rep.eng.slo
                if rep.role == "prefill":
                    slo.prefill_burst = max(slo.prefill_burst, 8)
                    slo.decode_burst = 1
                else:
                    slo.prefill_burst = 1
                    slo.decode_burst = max(slo.decode_burst, 8)
        # directory cost model constants: HBM bytes one page restores
        # (host→device) vs the FLOPs re-prefilling its tokens costs
        eng0 = self.replicas[0].eng
        self._page_bytes = eng0._mgr.page_hbm_bytes()
        st = eng0.model.stack
        d, ff, nl = st.embed_dim, st.dim_feedforward, st.num_layers
        self._flops_per_token = 2.0 * (
            nl * (4 * d * d + 2 * d * ff)
            + getattr(eng0.model, "vocab_size", 0) * d)
        # directory tier tracking: each replica's host tier reports
        # page movement between tiers through these callbacks
        for rep in self.replicas:
            ht = getattr(rep.eng, "host_tier", None)
            if ht is not None:
                ht.on_spill = (lambda key, i=rep.idx:
                               self._note_tier(key, i, "host"))
                ht.on_restore = (lambda key, i=rep.idx:
                                 self._note_tier(key, i, "hbm"))
                ht.on_drop = (lambda key, i=rep.idx:
                              self._drop_tier(key, i))
        self._rr = 0
        self._tracked: List[Request] = []
        self._dispatch_lock = threading.Lock()
        self._stop = False
        self._monitor: Optional[threading.Thread] = None
        #: per-replica TimeSeriesSamplers + the fleet scrape endpoint
        #: (``telemetry_samplers`` / ``start_telemetry``, ISSUE 16)
        self._samplers = None
        self._telemetry_srv = None
        #: walk the missed-beat state machine in ``check_health``.
        #: OFF in synchronous mode — one driver steps the replicas
        #: sequentially, so "replica 0 missed beats" only means the
        #: driver was busy stepping replica 1 (a several-second XLA
        #: compile would false-kill the whole fleet). ``start()``
        #: turns it on (each replica beats from its own thread);
        #: ManualClock tests set it explicitly. Crash detection
        #: (``crashed`` → dead → failover) is always on.
        self.enforce_beats = False
        # router-tier usage ledger (ISSUE 17): terminal records for
        # requests that die AT THE ROUTER (failover budget spent,
        # fleet shed) — ``fleet_usage`` folds it with every replica
        # engine's ledger into one record per request
        self.usage: Optional[UsageLedger] = None
        if _flag("usage_ledger"):
            self.usage = UsageLedger()
        # per-tenant quota state (ISSUE 18): submission timestamps for
        # the rate limit, and (timestamp, cumulative-token) marks the
        # rolling token budget differences against — all on the
        # injectable serving clock, all router-tier (one tenant's
        # burst backpressures that tenant alone, before any replica
        # admits)
        self._tenant_times: Dict[str, deque] = {}
        self._tenant_token_marks: Dict[str, deque] = {}
        self._quota_lock = threading.Lock()
        self.faults = None
        if faults is not None:
            self.install_faults(faults)
        self._update_gauges()

    # ---------------- faults ----------------

    def install_faults(self, faults) -> None:
        """Arm one seeded injector fleet-wide: the router sites
        (``router.dispatch``/``replica.step``/``replica.heartbeat``)
        fire here, and every replica engine wires its own sites
        (callable after construction so a chaos bench warms compile
        caches fault-free first). NOTE: ``squeeze`` specs target the
        LAST replica's page manager (the injector binds one)."""
        self.faults = faults
        for rep in self.replicas:
            rep.eng.install_faults(faults)

    # ---------------- submission / dispatch ----------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id=None, priority: int = 0, on_token=None,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               adapter_id: Optional[str] = None) -> int:
        """Route one request to a replica (affinity, then load/SLO)
        and return its fleet-unique id. ``tenant`` stamps the usage
        ledger's billing identity fleet-wide; ``adapter_id`` routes
        decode through that LoRA adapter on the serving replica (fleet
        replicas should share ONE AdapterBank so failover/migration
        re-resolves the same weights). Raises
        :class:`FleetOverloaded` when the fleet-wide dispatch queue is
        past ``FLAGS_fleet_dispatch_queue`` or no replica is
        dispatchable, and :class:`TenantQuotaExceeded` when the
        tenant is past its request-rate or rolling token quota
        (``FLAGS_tenant_quota_*``) — backpressure BEFORE any replica
        admits."""
        req = Request(prompt, max_new_tokens, eos_token_id,
                      priority=priority, on_token=on_token,
                      deadline_ms=deadline_ms, tenant=tenant,
                      adapter_id=adapter_id)
        try:
            self._check_tenant_quota(req)
            self._dispatch(req)
        except (FleetOverloaded, TenantQuotaExceeded):
            u = self.usage
            if u is not None:
                # router-tier shed still emits exactly one record
                u.finish(req, "shed")
            raise
        self._tracked.append(req)
        return req.id

    # ---------------- per-tenant quotas (ISSUE 18) ----------------

    def _check_tenant_quota(self, req: Request) -> None:
        """Router-tier per-tenant quota enforcement, BEFORE dispatch:

        - **request rate** (``FLAGS_tenant_quota_rps``): at most
          ``rps * window_s`` submissions per tenant within the rolling
          ``FLAGS_tenant_quota_window_s`` window (clock-seam
          timestamps — a ``ManualClock`` drives it deterministically);
        - **token budget** (``FLAGS_tenant_quota_tokens``): the
          tenant's prefill+decode tokens attributed by the FLEET usage
          ledger (ISSUE 17) within the same rolling window — requires
          ``FLAGS_usage_ledger`` (without it there is nothing to
          meter and the token leg is inert).

        Both shed with the typed :class:`TenantQuotaExceeded` (a
        ``ServerOverloaded`` subclass) so one tenant's burst
        backpressures that tenant alone. 0 disables each leg."""
        rps = float(_flag("tenant_quota_rps"))
        tok_cap = int(_flag("tenant_quota_tokens"))
        if rps <= 0 and tok_cap <= 0:
            return
        tenant = getattr(req, "tenant", None) or "default"
        window = max(float(_flag("tenant_quota_window_s")), 1e-9)
        now = _faults.now()
        with self._quota_lock:
            if rps > 0:
                dq = self._tenant_times.setdefault(tenant, deque())
                while dq and now - dq[0] >= window:
                    dq.popleft()
                if len(dq) >= rps * window:
                    _stats.inc("fleet.quota_sheds")
                    raise TenantQuotaExceeded(
                        tenant, "rate",
                        f"tenant {tenant!r}: {len(dq)} requests in "
                        f"the last {window}s >= quota "
                        f"{rps * window:g}")
                dq.append(now)
            if tok_cap > 0:
                cum = self._tenant_tokens(tenant)
                dq = self._tenant_token_marks.setdefault(
                    tenant, deque())
                dq.append((now, cum))
                # keep the newest mark at-or-before the window start
                # as the baseline the rolling usage differences from
                while len(dq) >= 2 and now - dq[1][0] >= window:
                    dq.popleft()
                used = cum - dq[0][1]
                if used > tok_cap:
                    _stats.inc("fleet.quota_sheds")
                    raise TenantQuotaExceeded(
                        tenant, "tokens",
                        f"tenant {tenant!r}: {used} tokens in the "
                        f"last {window}s > quota {tok_cap}")

    def _tenant_tokens(self, tenant: str) -> int:
        """Cumulative prefill+decode tokens the fleet ledgers have
        attributed to ``tenant`` (0 with the ledger off)."""
        roll = tenant_rollup(self.fleet_usage()).get(tenant)
        if roll is None:
            return 0
        return int(roll["prefill_tokens"]) + int(roll["decode_tokens"])

    def _dispatchable(self, exclude=frozenset(),
                      breaker: bool = True) -> List[Replica]:
        """Replicas new work may route to: alive first, suspect only
        as a last resort, open breakers (optionally) skipped."""
        alive, backup = [], []
        for rep in self.replicas:
            if rep.idx in exclude or rep.dead \
                    or rep.state == "draining" \
                    or rep.crashed is not None:
                continue
            if breaker and not rep.breaker.allow():
                continue
            (alive if rep.state == "alive" else backup).append(rep)
        return alive or backup

    def _load_score(self, rep: Replica):
        eng = rep.eng
        depth = eng.queue_depth + eng.num_prefilling + eng.num_active
        good = eng.slo_monitor.goodput
        return (depth, -(1.0 if good is None else good), rep.idx)

    @property
    def _affinity(self) -> Dict[bytes, int]:
        """Owner-only read view of the prefix directory (chain key →
        replica idx) — PR 14's affinity map, kept for callers that
        care WHO holds a prefix, not which memory tier holds it."""
        return {k: v[0] for k, v in self._directory.items()}

    def _affinity_chain(self, prompt) -> List[bytes]:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ps = self.page_size
        n = min(len(prompt) // ps, self.affinity_pages)
        keys, key = [], b""
        for p in range(n):
            key = _page_key(key, prompt[p * ps: (p + 1) * ps])
            keys.append(key)
        return keys

    def _note_tier(self, key: bytes, idx: int, tier: str) -> None:
        """Host-tier callback: chain ``key``'s pages moved between
        replica ``idx``'s memory tiers (spill → "host", restore →
        "hbm"). A key another replica already owns keeps its owner —
        an HBM holder elsewhere beats a host copy here."""
        if self.policy != "affinity":
            return
        ent = self._directory.get(key)
        if ent is None or ent[0] == idx:
            self._directory[key] = (idx, tier)

    def _drop_tier(self, key: bytes, idx: int) -> None:
        """Host-tier LRU eviction: the key left replica ``idx``'s host
        tier without restoring — forget the directory entry."""
        if self._directory.get(key) == (idx, "host"):
            self._directory.pop(key, None)

    def _pull_worth(self, pages: int) -> bool:
        """The directory cost model: restoring ``pages`` from a host
        tier moves ``pages * page_bytes`` over the assumed
        ``FLAGS_kv_restore_gbps`` host→HBM bandwidth; re-prefilling
        the tokens they cover burns ~2·params FLOPs per token at
        ``FLAGS_disagg_prefill_tflops``. Route to the host-tier holder
        only when the restore is the cheaper arm."""
        gbps = max(float(_flag("kv_restore_gbps")), 1e-9)
        tflops = max(float(_flag("disagg_prefill_tflops")), 1e-12)
        restore_s = pages * self._page_bytes / (gbps * 1e9)
        prefill_s = (pages * self.page_size * self._flops_per_token
                     / (tflops * 1e12))
        return restore_s < prefill_s

    def _candidate_order(self, req: Request,
                         cands: List[Replica]) -> List[Replica]:
        if self.policy == "rr":
            cands = sorted(cands, key=lambda r: r.idx)
            k = self._rr % len(cands)
            self._rr += 1
            return cands[k:] + cands[:k]
        by_load = sorted(cands, key=self._load_score)
        # longest matching chain wins: walk the prompt's chain keys
        # back-to-front so deeper (more specific) matches route first.
        # The directory verdict is counted once per dispatch: hit =
        # HBM holder found, pull = host-tier holder worth restoring,
        # miss = nothing known (or the cost model said re-prefill)
        by_idx = {r.idx: r for r in cands}
        chain = self._affinity_chain(req.prompt)
        for depth_back, key in enumerate(reversed(chain)):
            ent = self._directory.get(key)
            if ent is None:
                continue
            owner, tier = ent
            if owner not in by_idx:
                continue
            tgt = by_idx[owner]
            rest = [r for r in by_load if r is not tgt]
            if tier == "hbm":
                _stats.inc("fleet.directory_hits")
                return [tgt] + rest
            if self._pull_worth(len(chain) - depth_back):
                # route to the holder; its admission path restores
                # the chain from its host tier (restore_chain)
                _stats.inc("fleet.directory_pulls")
                return [tgt] + rest
            _stats.inc("fleet.directory_misses")
            return by_load
        if chain:
            _stats.inc("fleet.directory_misses")
        return by_load

    def _register_affinity(self, req: Request, rep: Replica) -> None:
        if self.policy != "affinity":
            return
        for key in self._affinity_chain(req.prompt):
            self._directory[key] = (rep.idx, "hbm")

    def _dispatch(self, req: Request, exclude=frozenset(),
                  force: bool = False) -> Replica:
        """Pick a replica and hand ``req`` to its admission inbox.
        ``force`` (failover/hedge/drain re-dispatch) bypasses both the
        router-tier queue bound and the per-engine overload check —
        the request was already admitted to the FLEET once. A dispatch
        error (injected fault, engine shed) counts against the chosen
        replica's breaker and the next candidate is tried."""
        with self._dispatch_lock:
            cands = self._dispatchable(exclude)
            if self.disagg is not None and not force:
                # role routing: NEW requests land on prefill-heavy
                # replicas (their finished slots hand off to the
                # decode side); with every prefill replica down the
                # decode side still serves — roles are a preference,
                # never an availability constraint
                pre = [r for r in cands if r.role == "prefill"]
                if pre:
                    cands = pre
            if not cands:
                _stats.inc("fleet.shed")
                raise FleetOverloaded(
                    f"request {req.id}: no dispatchable replica "
                    f"(states: "
                    f"{[r.state for r in self.replicas]})")
            cap = int(_flag("fleet_dispatch_queue"))
            if not force and cap > 0:
                depth = sum(r.eng.queue_depth for r in cands)
                if depth >= cap:
                    _stats.inc("fleet.shed")
                    raise FleetOverloaded(
                        f"request {req.id} shed at the router: "
                        f"fleet dispatch queue {depth} >= {cap}")
            fi = self.faults
            last: Optional[BaseException] = None
            for rep in self._candidate_order(req, cands):
                try:
                    if fi is not None:
                        fi.fire("router.dispatch", rid=req.id)
                    if force:
                        rep.eng.adopt_request(req)
                    else:
                        rep.eng.submit_request(req)
                except ValueError:
                    raise   # request/engine config mismatch — not a
                    # replica fault, don't burn its breaker
                except BaseException as e:
                    last = e
                    rep.breaker.record_failure()
                    self._update_gauges()
                    continue
                rep.breaker.record_success()
                self._register_affinity(req, rep)
                _stats.inc("fleet.dispatches")
                return rep
            _stats.inc("fleet.shed")
            raise FleetOverloaded(
                f"request {req.id}: every dispatch attempt failed "
                f"(last: {last!r})")

    # ---------------- health ----------------

    def check_health(self) -> None:
        """One health-checker pass on the serving clock: crashed loops
        go straight to dead; silent replicas walk
        alive → suspect (``FLAGS_fleet_suspect_beats`` missed beats)
        → dead (twice that); recovered beats walk suspect back to
        alive. Suspect entry hedges the replica's inbox; death fails
        its in-flight requests over."""
        hb = float(_flag("fleet_heartbeat_ms")) / 1e3
        sus = max(int(_flag("fleet_suspect_beats")), 1)
        now = _faults.now()
        for rep in self.replicas:
            if rep.dead:
                continue
            if rep.crashed is not None:
                self._mark_dead(rep, f"crashed: {rep.crashed!r}")
                continue
            if rep.state in ("draining", "drained"):
                # a drain is a deliberate exit from service, not a
                # silent failure: the draining thread is busy streaming
                # pages (it still beats between decode steps on the
                # async path) and a drained one stops beating forever
                continue
            if hb <= 0 or not self.enforce_beats:
                continue
            missed = (now - rep.last_beat) / hb
            if missed >= 2 * sus:
                self._mark_dead(
                    rep, f"missed {missed:.0f} heartbeats")
            elif missed >= sus:
                if rep.state == "alive":
                    rep.state = "suspect"
                    _stats.inc("fleet.suspects")
                    self._hedge(rep)
            elif rep.state == "suspect":
                rep.state = "alive"   # beats resumed
        self._update_gauges()

    def _update_gauges(self) -> None:
        # re-stamped every health pass so bench post-warmup
        # stats.reset() never erases the fleet shape
        _stats.set_gauge("fleet.replicas", len(self.replicas))
        _stats.set_gauge("fleet.replicas_alive",
                         sum(not r.dead for r in self.replicas))
        _stats.set_gauge("fleet.circuit_open",
                         sum(r.breaker.state != "closed"
                             for r in self.replicas))

    def kill(self, idx: int, exc: Optional[BaseException] = None) -> None:
        """Operator/test API: declare replica ``idx`` crashed and run
        the health pass (→ dead → failover) immediately."""
        rep = self.replicas[idx]
        rep.crashed = exc if exc is not None else ReplicaKilled(
            message=f"replica {idx} killed")
        self.check_health()

    def _mark_dead(self, rep: Replica, why: str) -> None:
        rep.state = "dead"
        jr = rep.eng.journal
        if jr is not None:
            # the dead replica's journal survives in host memory —
            # export_journals/serve_top show WHY its lane went dark
            jr.record("error", -1, -1,
                      {"replica": rep.idx, "reason": why[:200]})
        _stats.inc("fleet.deaths")
        self._update_gauges()
        self._failover(rep)

    # ---------------- failover / hedging ----------------

    def _fail(self, req: Request, exc: BaseException) -> None:
        """Terminal router-tier failure (failover budget spent / no
        replica left): the request — not the fleet — dies."""
        req.done = True
        req.state = "error"
        req.error = exc
        req.slo_ok = False
        req.t_done = _faults.now()
        u = self.usage
        if u is not None:
            u.finish(req, "error")
        _stats.inc("serving.request_errors")

    def _failover(self, rep: Replica) -> None:
        """Crash failover: strip every in-flight request off the dead
        replica and re-dispatch each through the recompute resume path
        (prompt + generated replayed on the survivor; greedy tokens
        byte-identical). A request past ``MAX_FAILOVERS`` — or with no
        healthy replica left — fails terminally instead of cascading.

        The detach briefly waits for the replica's step lock so a
        loop that crashed BETWEEN steps (the common case — injected
        kills fire before the engine mutates) is detached quietly;
        a replica wedged INSIDE a step keeps the lock forever, so
        after the timeout we detach anyway — it is dead and fenced
        (``step_once`` refuses dead replicas), and stranded pool
        pages die with its pool."""
        got = rep.step_lock.acquire(timeout=0.2)
        try:
            reqs = rep.eng.detach_inflight()
        finally:
            if got:
                rep.step_lock.release()
        if not reqs:
            return
        _stats.inc("fleet.failovers")
        for req in reqs:
            if req.generated:
                req._resume_tokens = np.concatenate(
                    [req.prompt,
                     np.asarray(req.generated, np.int32)])
            req.n_failovers = getattr(req, "n_failovers", 0) + 1
            if req.n_failovers > MAX_FAILOVERS:
                self._fail(req, ReplicaKilled(message=(
                    f"request {req.id} exceeded {MAX_FAILOVERS} "
                    "failovers — poison request dropped")))
                continue
            try:
                dest = self._dispatch(req, exclude={rep.idx},
                                      force=True)
            except FleetOverloaded as e:
                self._fail(req, e)
                continue
            _stats.inc("fleet.failover_requests")
            jr = dest.eng.journal
            if jr is not None:
                jr.record("failover", req.id, -1,
                          {"from": rep.idx, "to": dest.idx,
                           "n_generated": len(req.generated)})

    def _hedge(self, rep: Replica) -> None:
        """Suspect-entry hedging: requests still parked in the
        replica's admission INBOX (no KV state, and the inbox lock
        makes the steal race-free even against a live-but-slow
        replica) re-dispatch to a healthy peer instead of queueing
        behind a maybe-dead one."""
        with rep.eng._inbox_lock:
            stolen, rep.eng._inbox = rep.eng._inbox, []
        for req in stolen:
            _stats.inc("fleet.hedges")
            try:
                self._dispatch(req, exclude={rep.idx}, force=True)
            except FleetOverloaded as e:
                self._fail(req, e)

    # ---------------- graceful drain ----------------

    def drain(self, idx: int) -> None:
        """Gracefully drain replica ``idx``: dispatch stops, queued/
        prefilling requests re-dispatch to peers, and every mid-decode
        slot MIGRATES its KV pages to a healthy replica (page-granular
        handoff — no recompute; falls back to the resume path only
        when no peer can take the pages). Synchronous callers drain
        inline; in threaded mode the replica's own thread performs the
        drain so no step races the page export."""
        rep = self.replicas[idx]
        if rep.dead or rep.state == "draining":
            return
        rep.state = "draining"
        jr = rep.eng.journal
        if jr is not None:
            jr.record("drain", -1, -1, {"replica": idx})
        if rep.thread is None or not rep.thread.is_alive():
            self._drain_now(rep)

    def _drain_now(self, rep: Replica) -> None:
        eng = rep.eng
        use_async = bool(_flag("migrate_async")) and eng.can_migrate()
        with rep.step_lock:
            with eng._inbox_lock:
                queued, eng._inbox = eng._inbox, []
            queued += eng.waiting
            eng.waiting = []
            for i in sorted(eng._prefilling):
                req = eng._prefilling[i].req
                eng._drop_prefill_slot(i)
                queued.append(req)
            for req in queued:
                if req.generated:   # a preempted-then-requeued stream
                    req._resume_tokens = np.concatenate(
                        [req.prompt,
                         np.asarray(req.generated, np.int32)])
                self._redispatch_from(rep, req)
        if use_async:
            # decode-concurrent streaming: NO step lock held across
            # the per-slot page streams (both endpoints keep decoding)
            self._drain_async(rep)
        with rep.step_lock:
            if not use_async:
                for i in range(eng.max_batch):
                    if eng._slots[i] is None:
                        continue
                    req = eng._slots[i]
                    if not self._migrate_slot(rep, i):
                        # no peer took the pages — recompute resume
                        req._resume_tokens = np.concatenate(
                            [req.prompt,
                             np.asarray(req.generated, np.int32)])
                        eng._release(i)
                        self._redispatch_from(rep, req)
            if eng.prefix_cache is not None:
                # the replica leaves service: hand its pages back so
                # the drain's page accounting closes exactly
                eng.prefix_cache.clear()
        rep.state = "drained"
        jr = eng.journal
        if jr is not None:
            jr.record("drain", -1, -1,
                      {"replica": rep.idx, "done": True})
        self._update_gauges()

    def _redispatch_from(self, rep: Replica, req: Request) -> None:
        try:
            self._dispatch(req, exclude={rep.idx}, force=True)
        except FleetOverloaded as e:
            self._fail(req, e)

    def _migrate_slot(self, src: Replica, i: int,
                      event: str = "migrate",
                      dest_role: Optional[str] = None) -> bool:
        """Hand decode slot ``i``'s KV pages from ``src`` to a healthy
        peer: export (gather), import (alloc + put + slot re-home),
        THEN release the source pages — a failed import leaves the
        source untouched. Counted in ``fleet.{migrations,
        migrated_pages}`` (``fleet.{handoffs,handoff_pages}`` when
        ``event="handoff"`` — the disaggregated prefill→decode path)
        and journaled on the destination's lane. ``dest_role``
        restricts candidate peers to one disaggregation role."""
        eng = src.eng
        if not eng.can_migrate():
            return False
        req = eng._slots[i]
        # cheap racy pre-check: skip the whole-slot export when no
        # candidate has a landing slot right now (the authoritative
        # check re-runs under the destination's step lock below) —
        # a handoff retries every source step, so a full gather per
        # doomed attempt would tax exactly the prefill steps the
        # disaggregated split is trying to protect
        if not any(d.eng.can_migrate()
                   and (dest_role is None or d.role == dest_role)
                   and any(d.eng._slot_free(j)
                           for j in range(d.eng.max_batch))
                   for d in self._dispatchable(exclude={src.idx})):
            return False
        tm0 = _faults.now()
        blob = eng.export_slot(i)
        for dest in self._dispatchable(exclude={src.idx}):
            if not dest.eng.can_migrate():
                continue
            if dest_role is not None and dest.role != dest_role:
                continue
            with dest.step_lock:
                j = next((j for j in range(dest.eng.max_batch)
                          if dest.eng._slot_free(j)), None)
                if j is None or not dest.eng.import_slot(j, blob):
                    continue
            req.n_migrations = getattr(req, "n_migrations", 0) + 1
            eng._release(i)   # src ledger closes its page integral
            if event == "handoff":
                _stats.inc("fleet.handoffs")
                _stats.inc("fleet.handoff_pages", blob["n_pages"])
            else:
                _stats.inc("fleet.migrations")
                _stats.inc("fleet.migrated_pages", blob["n_pages"])
            # the migration phase of serving-time attribution: export
            # through release, stamped via the clock seam (failed
            # attempts are not a phase — nothing moved). The ledger
            # charges the migrated request the SAME float on the
            # DESTINATION replica — where its record continues
            mig_ms = (_faults.now() - tm0) * 1e3
            ud = dest.eng.usage
            if ud is not None:
                ud.set_pages(req, blob["n_pages"])
                ud.charge_phase("migration", mig_ms, (req,))
            _stats.observe("serve.step.migration_ms", mig_ms)
            jr = dest.eng.journal
            if jr is not None:
                jr.record(event, req.id, j,
                          {"from": src.idx, "to": dest.idx,
                           "pages": blob["n_pages"],
                           "n_generated": len(req.generated)})
            return True
        return False

    #: page batch size of one async-migration stream step — small
    #: enough that the destination's per-batch scatter critical
    #: section stays shorter than a decode step
    ASYNC_MIGRATE_BATCH_PAGES = 2

    def _drain_async(self, rep: Replica) -> None:
        """Decode-concurrent drain (``FLAGS_migrate_async``): each
        occupied slot's COMPLETE pages stream to a peer in page
        batches with no step lock held on the source — the source
        keeps taking decode steps between batches (driven right here:
        the drain owns the replica's thread) and the destinations
        keep serving on their own threads. The join copies only the
        mutable tail + metadata under both step locks, so zero-loss
        and byte-identical continuation are preserved: a complete
        page never mutates under append-only decode."""
        eng = rep.eng
        for i in range(eng.max_batch):
            req = eng._slots[i]
            if req is None:
                continue
            if not self._migrate_slot_async(rep, i):
                with rep.step_lock:
                    if eng._slots[i] is not req:
                        continue      # finished while we tried
                    req._resume_tokens = np.concatenate(
                        [req.prompt,
                         np.asarray(req.generated, np.int32)])
                    eng._release(i)
                self._redispatch_from(rep, req)

    def _migrate_slot_async(self, src: Replica, i: int,
                            event: str = "migrate",
                            dest_role: Optional[str] = None) -> bool:
        """Stream decode slot ``i`` to a peer while BOTH endpoints
        keep decoding: reserve pages on the destination (short lock),
        copy complete pages batch-by-batch (source lock-free, one
        short destination lock per batch, a decode step on the source
        between batches), then join — tail pages + slot metadata —
        under both step locks. True when the slot landed on a peer OR
        finished on the source mid-stream; False sends the caller to
        the recompute fallback."""
        from ..profiler import RecordEvent

        eng = src.eng
        if not eng.can_migrate():
            return False
        req = eng._slots[i]
        if req is None:
            return True
        tm0 = _faults.now()
        n0 = len(eng._mgr._owned.get(("slot", i), ()))
        dest = ticket = None
        for cand in self._dispatchable(exclude={src.idx}):
            if not cand.eng.can_migrate():
                continue
            if dest_role is not None and cand.role != dest_role:
                continue
            with cand.step_lock:
                t = cand.eng.import_begin(n0)
            if t is not None:
                dest, ticket = cand, t
                break
        if dest is None:
            return False
        streamed = 0
        with RecordEvent("fleet.migrate.stream"):
            while True:
                if eng._slots[i] is not req:
                    # finished on the source mid-stream: nothing left
                    # to move — the reservation dies, the request
                    # already completed where it was
                    with dest.step_lock:
                        dest.eng.import_abort(ticket)
                    return True
                safe = min(eng.safe_page_count(i), ticket["n_pages"])
                if streamed >= safe:
                    break
                hi = min(streamed + self.ASYNC_MIGRATE_BATCH_PAGES,
                         safe)
                try:
                    batch = eng.export_pages(i, streamed, hi)
                except KeyError:
                    continue   # slot released between check and read
                with dest.step_lock:
                    dest.eng.import_pages(ticket, batch)
                streamed = hi
                # the source's decode batch keeps moving between
                # stream batches (the drain owns this thread)
                src.step_once()
        first, second = (src, dest) if src.idx < dest.idx \
            else (dest, src)
        with first.step_lock, second.step_lock:
            if eng._slots[i] is not req:
                dest.eng.import_abort(ticket)
                return True
            blob = eng.export_slot_tail(i, streamed)
            j = next((j for j in range(dest.eng.max_batch)
                      if dest.eng._slot_free(j)), None)
            if j is None or not dest.eng.import_finish(ticket, j,
                                                       blob):
                dest.eng.import_abort(ticket)
                return False
            req.n_migrations = getattr(req, "n_migrations", 0) + 1
            eng._release(i)   # src ledger closes its page integral
            n_pages = blob["n_pages"]
        if event == "handoff":
            _stats.inc("fleet.handoffs")
            _stats.inc("fleet.handoff_pages", n_pages)
        else:
            _stats.inc("fleet.migrations")
            _stats.inc("fleet.migrated_pages", n_pages)
        _stats.inc("fleet.async_migrations")
        mig_ms = (_faults.now() - tm0) * 1e3
        ud = dest.eng.usage
        if ud is not None:
            ud.set_pages(req, n_pages)
            ud.charge_phase("migration", mig_ms, (req,))
        _stats.observe("serve.step.migration_ms", mig_ms)
        jr = dest.eng.journal
        if jr is not None:
            jr.record(event, req.id, j,
                      {"from": src.idx, "to": dest.idx,
                       "pages": n_pages, "async": True,
                       "n_generated": len(req.generated)})
        return True

    # ------------- disaggregated handoff (ISSUE 20) -------------

    def _handoff_ready(self, rep: Replica) -> int:
        """Move a prefill replica's decoding slots to the decode side:
        a slot whose chunk prefill finished is pure decode work from
        here on, and every step it stays is a decode step competing
        with this replica's prefill bursts. Each occupied slot rides
        the export/import migration path (page-streamed async under
        ``FLAGS_migrate_async``) to a decode-role replica — journaled
        as ``handoff``, counted in ``fleet.{handoffs,handoff_pages}``.
        A slot no decode replica can take just keeps decoding here:
        the handoff is an optimization, never a correctness step.
        Call from the replica's own stepping thread (or the
        synchronous driver) so the export never races a decode."""
        if self.disagg is None or rep.role != "prefill" or rep.dead \
                or rep.crashed is not None:
            return 0
        eng = rep.eng
        if not eng.can_migrate():
            return 0
        use_async = bool(_flag("migrate_async"))
        moved = 0
        for i in range(eng.max_batch):
            req = eng._slots[i]
            if req is None:
                continue
            if req.max_new_tokens - len(req.generated) < 2:
                continue   # finishing anyway — not worth the copy
            if use_async:
                ok = self._migrate_slot_async(rep, i, event="handoff",
                                              dest_role="decode")
            else:
                ok = self._migrate_slot(rep, i, event="handoff",
                                        dest_role="decode")
            moved += bool(ok)
        return moved

    # ---------------- driving ----------------

    def step(self) -> bool:
        """One synchronous fleet step: a health pass, then one
        scheduler step per live replica (tests and the dryrun drive
        this; ``start()`` runs the same loop on one thread per
        replica). Returns whether any replica did work."""
        self.check_health()
        did = False
        for rep in self.replicas:
            worked = rep.step_once()
            did = worked or did
            if worked and rep.role == "prefill":
                self._handoff_ready(rep)
        return did

    def pending(self) -> int:
        """Tracked requests not yet in a terminal state."""
        return sum(not r.done for r in self._tracked)

    def run(self, max_steps: int = 200_000) -> List[Request]:
        """Synchronous drain: step until every tracked request reaches
        a terminal state (ok / error / deadline_exceeded / shed)."""
        steps = 0
        while self.pending():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet stalled: {self.pending()} requests still "
                    f"in flight after {max_steps} steps (replica "
                    f"states: {[r.state for r in self.replicas]})")
        return list(self._tracked)

    def start(self) -> None:
        """Threaded mode: one serve-loop thread per replica plus a
        health-monitor thread (real clock). ``stop()`` joins them.
        Beat enforcement turns on here — each replica now beats from
        its own thread, so a silent one really is wedged."""
        self._stop = False
        self.enforce_beats = True
        for rep in self.replicas:
            rep.last_beat = _faults.now()   # fresh grace period
        for rep in self.replicas:
            if rep.thread is None or not rep.thread.is_alive():
                rep.thread = threading.Thread(
                    target=rep._loop, daemon=True,
                    name=f"fleet-replica-{rep.idx}")
                rep.thread.start()
        if self._monitor is None or not self._monitor.is_alive():
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="fleet-monitor")
            self._monitor.start()

    def _monitor_loop(self) -> None:
        hb = max(float(_flag("fleet_heartbeat_ms")), 1.0) / 1e3
        while not self._stop:
            self.check_health()
            time.sleep(hb / 2)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop = True
        for rep in self.replicas:
            if rep.thread is not None:
                rep.thread.join(timeout)
        if self._monitor is not None:
            self._monitor.join(timeout)

    # ---------------- results / introspection ----------------

    def requests(self) -> List[Request]:
        """Every tracked request, in submission order."""
        return list(self._tracked)

    def results(self) -> Dict[int, Request]:
        """Tracked requests keyed by id."""
        return {r.id: r for r in self._tracked}

    def export_journals(self, dirpath: str,
                        prefix: str = "fleet_journal") -> List[str]:
        """Dump each replica's flight recorder as
        ``<prefix>_r<idx>.jsonl`` (tools/serve_top.py --fleet input;
        chrome traces exported from them with pid = replica id fold
        through tools/trace_merge.py)."""
        import os

        paths = []
        for rep in self.replicas:
            if rep.eng.journal is None:
                continue
            p = os.path.join(dirpath, f"{prefix}_r{rep.idx}.jsonl")
            rep.eng.journal.dump_jsonl(p)
            paths.append(p)
        return paths

    def fleet_usage(self) -> List[dict]:
        """The FLEET usage ledger: every replica's per-request records
        plus the router's own terminal records, folded to ONE record
        per request (``serving.accounting.fold_records`` — integer
        phase_ns sums add exactly, the single non-None terminal state
        survives), so a failed-over or migrated request is charged
        exactly once fleet-wide."""
        recs: List[dict] = []
        for rep in self.replicas:
            u = rep.eng.usage
            if u is not None:
                recs.extend(u.records(include_open=True,
                                      hop=rep.idx))
        if self.usage is not None:
            recs.extend(self.usage.records(include_open=True,
                                           hop=-1))
        return fold_records(recs)

    def export_usage(self, dirpath: str,
                     prefix: str = "fleet_usage") -> List[str]:
        """Dump each replica's usage ledger as
        ``<prefix>_r<idx>.jsonl`` (hop-stamped) plus the router's as
        ``<prefix>_router.jsonl`` — tools/trace_merge.py folds them
        back into the ``fleet_usage`` view offline."""
        import os

        paths = []
        for rep in self.replicas:
            u = rep.eng.usage
            if u is None:
                continue
            p = os.path.join(dirpath, f"{prefix}_r{rep.idx}.jsonl")
            u.dump_jsonl(p, hop=rep.idx)
            paths.append(p)
        if self.usage is not None:
            p = os.path.join(dirpath, f"{prefix}_router.jsonl")
            self.usage.dump_jsonl(p, hop=-1)
            paths.append(p)
        return paths

    def export_traces(self, dirpath: str,
                      prefix: str = "fleet_trace") -> List[str]:
        """One chrome trace per replica, REPLICA-STAMPED (``pid =
        replica id``, one lane per request) — feed them straight
        through ``tools/trace_merge.py`` for a single fleet timeline
        where a failover/migration hop shows the request's lane
        continuing on the destination replica's pid."""
        import json as _json
        import os

        from .journal import chrome_trace

        paths = []
        for rep in self.replicas:
            if rep.eng.journal is None:
                continue
            p = os.path.join(dirpath, f"{prefix}_r{rep.idx}.json")
            with open(p, "w") as f:
                _json.dump(chrome_trace(rep.eng.journal.events(),
                                        process_index=rep.idx), f)
            paths.append(p)
        return paths

    # ---------------- continuous telemetry (ISSUE 16) ----------------

    def telemetry_samplers(self, interval_ms: Optional[float] = None,
                           window: Optional[int] = None, clock=None):
        """One :class:`profiler.timeseries.TimeSeriesSampler` PER
        REPLICA, each reading its engine's live state directly
        (``engine_source`` — the process-wide stats registry is shared
        by every replica, so per-replica series must come from the
        engine objects). Built once; repeated calls return the same
        samplers so folds and exporters see one history."""
        from ..profiler.timeseries import TimeSeriesSampler
        from ..profiler.timeseries import engine_source

        if self._samplers is None:
            self._samplers = [
                TimeSeriesSampler(interval_ms=interval_ms,
                                  window=window, clock=clock,
                                  source=engine_source(rep.eng),
                                  enabled=True)
                for rep in self.replicas]
        return self._samplers

    def telemetry_tick(self) -> None:
        """Sample every replica once (synchronous drives; threaded
        serves use ``start_telemetry`` instead)."""
        for s in self.telemetry_samplers():
            s.tick()

    def fleet_series(self):
        """The FLEET-LEVEL series: per-replica ticks folded with the
        trace_merge semantics (counters SUM — replica completions add
        exactly; gauges MAX; histogram pairs SUM)."""
        from ..profiler.timeseries import aggregate_ticks

        return aggregate_ticks(
            [s.ticks() for s in self.telemetry_samplers()])

    def start_telemetry(self, port: Optional[int] = None,
                        interval_ms: Optional[float] = None):
        """Start the per-replica background samplers and (when
        ``port`` / ``FLAGS_telemetry_port`` is nonzero) ONE scrape
        endpoint serving the fleet fold's latest tick alongside the
        full process registry — N replicas, one port. Returns the
        :class:`profiler.timeseries.TelemetryServer` or None."""
        from ..profiler import timeseries as _ts

        for s in self.telemetry_samplers(interval_ms=interval_ms):
            s.start()

        def render():
            series = self.fleet_series()
            return _ts.tick_prometheus_text(series[-1]) \
                if series else ""

        if self._telemetry_srv is None:
            self._telemetry_srv = _ts.start_http_server(port, render)
        return self._telemetry_srv

    def stop_telemetry(self) -> None:
        """Stop the samplers (one final tick each) and the endpoint;
        the rings stay readable (``fleet_series`` still folds)."""
        if self._samplers is not None:
            for s in self._samplers:
                s.stop()
        if self._telemetry_srv is not None:
            self._telemetry_srv.stop()
            self._telemetry_srv = None
