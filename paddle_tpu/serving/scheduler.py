"""SLO-aware serving frontend over the continuous-batching engine.

The kernels under this (PR 4–5 fused weight-stream decode) are fast;
what turns them into a SERVICE is the layer here (ROADMAP item 2): an
async admission queue feeding a scheduler that interleaves CHUNKED
PREFILL with grouped decode — a 4k-token prompt fills the paged pool
in fixed-size chunks BETWEEN decode chunks, so admitting it never
stalls the decode batch for its whole prompt length — plus prefix/KV
reuse (serving/prefix_cache.py) so requests sharing a system prompt
map the prefix's pages instead of recomputing them.

Scheduling policy (``SLOConfig``): admission uses the engine's bounded
skip-ahead (head-of-line fix) ordered by request priority; the
prefill-vs-decode interleave is a weighted cycle derived from the
TTFT-vs-TPOT weights — ``ttft_weight : tpot_weight`` of 2:1 runs up to
two prefill chunks per decode chunk (new requests reach their first
token sooner), 1:2 the reverse (active streams keep their inter-token
gap tight). With any decode-ready request present, at most
``prefill_burst`` consecutive prefill chunks ever run, so an active
request's inter-token stall is BOUNDED by
``prefill_burst * prefill_chunk + decode_chunk`` tokens of device work
— the tier-1 stall-bound test pins this.

Telemetry (the PR 1–2 stats/roofline stack): per-request
``serve.{ttft_ms,tpot_ms,queue_wait_ms}`` histograms,
``serving.prefix_{hit,miss,pages_saved}`` + chunk counters, and every
scheduler phase reports under its own roofline rung —
``serve.prefill[c=N]`` per chunk size (honest post-sync timing) next
to the engine's ``decode.*[k=N]`` rungs.

Observability (PR 9): every lifecycle transition additionally lands in
the FLIGHT RECORDER (``serving/journal.py``, ``FLAGS_serve_journal``)
— a bounded ring journal from which one request's whole life is
reconstructable post-mortem — and every finish feeds the SLO monitor
(``serving/slo.py``: per-request TTFT/TPOT verdicts, rolling
``slo.goodput``, burn rate). ``run()`` dumps the journal tail + a
stats snapshot + every still-unserved request to a JSONL crash
artifact on any raise (``crash_dump``), so a production stack trace
always arrives with the request timelines that led to it.

Speculative decoding (ISSUE 12): constructed with ``speculative=``
(and optionally ``spec_k=``), the engine's decode slot of the
SLO-weighted interleave cycle runs DRAFT+VERIFY rounds instead of
token-by-token chunks (``inference/speculative.py`` — one streamed
``serve.verify[k=*,mp=N]`` pass per accepted window, greedy parity by
construction). It composes with everything here: chunked prefill
interleaves unchanged, preemption-by-recompute resets the drafter
slot so a resumed request re-drafts, accepted tokens count as
watchdog/deadline progress, and under TP the verify pass shard_maps
like ``prefill_chunk_raw`` while draft weights stay replicated. Each
round lands a ``spec_verify[k,accepted]`` journal event and the
``serve.accept_len`` histogram; serve_top renders the accept-rate
row.

Failure semantics (ISSUE 11 — see README "Failure semantics"): one
request's failure must never take the loop down. Per-request
``deadline_ms`` aborts a request wherever it sits (queue/prefill/
decode) and frees its pages; an exception inside one slot's
prefill/decode chunk retries with capped exponential backoff
(``FLAGS_serve_step_retries`` / ``FLAGS_serve_retry_backoff_ms``)
through the injectable serving clock, then errors out ONLY the
offending request; a progress watchdog
(``FLAGS_serve_watchdog_steps``) preempts/requeues a request that
stopped emitting tokens, and kills it on the second trip; admission
sheds with a typed ``ServerOverloaded`` when the (bounded) inbox,
queue depth, or SLO burn rate crosses its threshold — after the
scheduler has already degraded gracefully by shrinking prefill chunks
under pool pressure. All of it drivable deterministically by the
seeded fault registry in ``serving/faults.py``.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.flags import flag as _flag
from ..incubate.nn.fused_transformer import PagedKV
from ..inference.engine import ContinuousBatchingEngine, FusedCausalLM
from ..profiler import roofline as _roofline
from ..profiler import stats as _stats
from . import faults as _faults
from .accounting import UsageLedger
from .faults import (DeadlineExceeded, PoolSizingError, ServerOverloaded,
                     TokenCorruption, WatchdogTimeout)
from .journal import FlightRecorder
from .prefix_cache import PrefixCache
from .request import Request
from .slo import SLOMonitor

__all__ = ["SLOConfig", "ServingEngine"]


class SLOConfig:
    """Scheduler knobs (see module docstring for the policy).

    ``ttft_weight`` / ``tpot_weight``: relative urgency of prefill
    (time-to-first-token) vs decode (time-per-output-token) work; the
    integer interleave cycle is derived from their ratio.
    ``prefill_chunk``: tokens per chunked-prefill program (the stall
    bound's unit; one compiled program serves every chunk of this size).
    ``admit_window`` / ``starvation_bound``: admission skip-ahead reach
    and its fairness bound (inference/engine.py ``_pick_waiting``).
    ``prefix_cache``: enable prefix/KV reuse; ``prefix_cache_pages``
    caps the registered pages (None = pool-pressure eviction only).
    ``ttft_target_ms`` / ``tpot_target_ms``: per-request SLO targets
    the monitor (serving/slo.py) judges verdicts against (None
    disables that leg); ``goodput_objective`` + ``slo_window`` shape
    the rolling ``slo.goodput`` gauge and its burn rate.
    ``tenant_fair``: replace priority-FIFO admission with
    DEFICIT-WEIGHTED round-robin over per-tenant queues (ISSUE 18) —
    each admission round credits every waiting tenant
    ``fair_quantum * weight`` tokens of deficit, the richest tenant's
    earliest admissible request admits and pays its token cost
    (prompt + max_new), so a flooding tenant cannot starve a light
    one; the engine's ``starvation_bound`` still caps how long ANY
    head-of-queue request can be passed over. ``tenant_weights`` maps
    tenant name -> relative share (missing tenants weigh 1.0).
    """

    def __init__(self, ttft_weight: float = 1.0,
                 tpot_weight: float = 1.0, prefill_chunk: int = 256,
                 admit_window: int = 8, starvation_bound: int = 16,
                 prefix_cache: bool = True,
                 prefix_cache_pages: Optional[int] = None,
                 ttft_target_ms: Optional[float] = 1000.0,
                 tpot_target_ms: Optional[float] = 100.0,
                 goodput_objective: float = 0.99,
                 slo_window: int = 256, tenant_fair: bool = False,
                 tenant_weights: Optional[dict] = None,
                 fair_quantum: int = 256):
        if ttft_weight <= 0 or tpot_weight <= 0:
            raise ValueError("SLO weights must be positive")
        self.ttft_weight = float(ttft_weight)
        self.tpot_weight = float(tpot_weight)
        self.prefill_chunk = max(int(prefill_chunk), 1)
        self.admit_window = max(int(admit_window), 1)
        self.starvation_bound = max(int(starvation_bound), 1)
        self.prefix_cache = bool(prefix_cache)
        self.prefix_cache_pages = prefix_cache_pages
        self.ttft_target_ms = None if ttft_target_ms is None \
            else float(ttft_target_ms)
        self.tpot_target_ms = None if tpot_target_ms is None \
            else float(tpot_target_ms)
        if not 0.0 < float(goodput_objective) < 1.0:
            raise ValueError("goodput_objective must be in (0, 1)")
        self.goodput_objective = float(goodput_objective)
        self.slo_window = max(int(slo_window), 1)
        self.tenant_fair = bool(tenant_fair)
        self.tenant_weights = dict(tenant_weights or {})
        self.fair_quantum = max(int(fair_quantum), 1)
        r = self.ttft_weight / self.tpot_weight
        #: consecutive prefill chunks allowed while decoders wait /
        #: decode chunks between prefill opportunities — the weighted
        #: interleave cycle (1:1 → strict alternation)
        self.prefill_burst = max(1, int(round(r)))
        self.decode_burst = max(1, int(round(1.0 / r)))


class _Prefill:
    """Progress of one chunk-prefilling request parked on a slot.

    ``tokens`` is what gets prefilled: the prompt, or — for a request
    preempted out of a decode slot under pool pressure — the prompt
    plus everything already generated, so the final chunk's logits
    yield the NEXT token of the stream (recompute-style resume)."""

    __slots__ = ("req", "pos", "tokens")

    def __init__(self, req: Request, pos: int, tokens):
        self.req = req
        self.pos = pos  # tokens already in the pool
        self.tokens = tokens


class ServingEngine(ContinuousBatchingEngine):
    """Production-shaped serving frontend (see module docstring).

    Usage::

        eng = ServingEngine(model, max_batch=8,
                            slo=SLOConfig(prefill_chunk=128))
        eng.submit([1, 2, 3], max_new_tokens=16,
                   on_token=lambda r, t: push(t))   # any thread
        finished = eng.run()        # or step() on the serving thread

    Chunk-prefilling requests park on a slot under a side page-table
    key (``("prefill", i)``) so the decode batch's slot tables never
    see their half-filled pages; completion rekeys the pages to
    ``("slot", i)`` and the request joins the decode batch with its
    first token already emitted (from the final chunk's logits).
    """

    def __init__(self, model: FusedCausalLM,
                 slo: Optional[SLOConfig] = None, faults=None,
                 adapters=None, **engine_kwargs):
        slo = slo or SLOConfig()
        engine_kwargs.setdefault("admit_window", slo.admit_window)
        engine_kwargs.setdefault("starvation_bound",
                                 slo.starvation_bound)
        super().__init__(model, **engine_kwargs)
        self.slo = slo
        # multi-LoRA adapter bank (ISSUE 18, serving/adapters.py):
        # None serves the base model only; a bank may be SHARED by
        # fleet replicas (refcounts key on request id). Requests pin
        # their adapter at submit and release at every terminal path.
        self.adapters = adapters
        # deficit-weighted round-robin state (SLOConfig.tenant_fair):
        # tenant -> accumulated token deficit
        self._fair_deficit: Dict[str, float] = {}
        # flight recorder (FLAGS_serve_journal): None when disabled,
        # so every hot-path hook is a single attribute test — no
        # event tuples or extra dicts are ever allocated
        self.journal: Optional[FlightRecorder] = None
        if _flag("serve_journal"):
            self.journal = FlightRecorder(
                int(_flag("serve_journal_events")))
        self._journal = self.journal  # base-engine finish hook
        self.slo_monitor = SLOMonitor(
            ttft_target_ms=slo.ttft_target_ms,
            tpot_target_ms=slo.tpot_target_ms,
            objective=slo.goodput_objective, window=slo.slo_window)
        # usage ledger (ISSUE 17, FLAGS_usage_ledger): None when
        # disabled, so — exactly like the journal — every hot-path
        # hook is a single attribute test with zero allocations
        self.usage: Optional[UsageLedger] = None
        if _flag("usage_ledger"):
            self.usage = UsageLedger()
        self._usage = self.usage  # engine/speculative token hooks
        self.last_crash_dump: Optional[str] = None
        self.prefix_cache: Optional[PrefixCache] = None
        if slo.prefix_cache:
            self.prefix_cache = PrefixCache(
                self._mgr, self.page_size, slo.prefix_cache_pages,
                journal=self.journal)
        # host-DRAM KV tier (ISSUE 20, FLAGS_kv_host_tier_bytes):
        # evicted prefix pages and preempted-slot pages spill to host
        # buffers behind the prefix cache's chain keys instead of being
        # recomputed; None (flag 0, no prefix cache, or a TP-sharded
        # pool) keeps every spill site one attribute test
        self.host_tier = None
        if self.prefix_cache is not None \
                and int(_flag("kv_host_tier_bytes") or 0) > 0 \
                and self.can_spill():
            from .host_tier import HostKVTier

            self.host_tier = HostKVTier(
                self, int(_flag("kv_host_tier_bytes")),
                journal=self.journal)
            self.prefix_cache.host_tier = self.host_tier
        self._prefilling: Dict[int, _Prefill] = {}
        # async admission: submit() appends here from ANY thread; the
        # scheduler thread drains into the priority-ordered waiting
        # list at each step
        self._inbox: List[Request] = []
        self._inbox_lock = threading.Lock()
        self._arrival = itertools.count()
        self._chunk_jit: dict = {}
        self._cycle_pos = 0
        #: scheduler action trace ("prefill"/"decode"), the stall-bound
        #: test's evidence; cheap (one short str per step)
        self.action_log: List[str] = []
        # crash-isolation bookkeeping (ISSUE 11): the request/slot a
        # risky phase is operating on (so its failure can be pinned to
        # the offending request), and the decode-chunk retry budget
        # (decode failures aren't attributable to one slot until the
        # budget is spent)
        self._admitting = None            # (req, slot) mid-_admit_into
        self._prefill_active = None       # (req, slot) mid-chunk
        self._decode_retries = 0
        # fault injection (serving/faults.py): installed on the engine,
        # the page manager (kv.alloc/kv.grow sites + squeeze target)
        # and the prefix cache; None keeps every site one attr test
        self.faults = None
        if faults is not None:
            self.install_faults(faults)

    def install_faults(self, faults) -> None:
        """Arm a :class:`~paddle_tpu.serving.faults.FaultInjector` on
        every wired site — the engine itself (``prefill.dispatch``,
        ``decode.step``, ``journal.dump``), the page manager
        (``kv.alloc``/``kv.grow`` + the squeeze target) and the prefix
        cache (``prefix.insert``). Callable after construction so a
        chaos bench can warm compile caches fault-free first."""
        self.faults = faults
        self._faults = faults             # base-engine decode.step site
        faults.bind(mgr=self._mgr, journal=self.journal)
        self._mgr._faults = faults
        if self.prefix_cache is not None:
            self.prefix_cache._faults = faults

    # ---------------- public API ----------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id=None, priority: int = 0,
               on_token=None, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               adapter_id: Optional[str] = None) -> int:
        """Thread-safe admission (any thread): queue a request, return
        its id. Tokens stream through ``on_token`` as they decode.
        ``deadline_ms`` bounds the request's whole life from arrival
        (see README "Failure semantics"); ``tenant`` stamps the usage
        ledger's billing identity (None bills to the default tenant);
        ``adapter_id`` routes decode through that LoRA adapter in the
        engine's :class:`~paddle_tpu.serving.adapters.AdapterBank`
        (the adapter is pinned against unload until this request
        terminates). Raises :class:`ServerOverloaded` — backpressure
        to the SUBMITTING thread — when the bounded inbox, the queue
        depth, or the SLO burn rate is past its shed threshold; a
        ``KeyError`` rejects an unknown or draining adapter."""
        req = Request(prompt, max_new_tokens, eos_token_id,
                      priority=priority, on_token=on_token,
                      deadline_ms=deadline_ms, tenant=tenant,
                      adapter_id=adapter_id)
        return self.submit_request(req)

    def submit_request(self, req: Request) -> int:
        if len(req.prompt) + req.max_new_tokens > self.max_length:
            raise ValueError("request exceeds engine max_length")
        self._check_overload(req)
        self._adapter_acquire(req)
        with self._inbox_lock:
            self._inbox.append(req)
        jr = self.journal
        if jr is not None:
            extra = {"prompt_len": int(len(req.prompt)),
                     "max_new": int(req.max_new_tokens)}
            if getattr(req, "tenant", None) is not None:
                extra["tenant"] = req.tenant
            if getattr(req, "adapter_id", None) is not None:
                extra["adapter"] = req.adapter_id
            jr.record("submit", req.id, -1, extra)
        _stats.inc("serve.submitted")
        return req.id

    # ---------------- multi-LoRA lifecycle (ISSUE 18) ----------------

    def _adapter_acquire(self, req: Request) -> None:
        """Pin ``req``'s adapter in this engine's bank and stamp the
        resolved bank slot on the request. Raises before the request
        enters any queue: an unknown/draining adapter (``KeyError``
        from the bank) or an adapter on a bank-less engine
        (``ValueError``) surfaces to the submitting thread."""
        name = getattr(req, "adapter_id", None)
        if name is None:
            return
        if self.adapters is None:
            raise ValueError(
                f"request {req.id} names adapter {name!r} but the "
                "engine has no adapter bank")
        if self._spec is not None:
            raise ValueError(
                "adaptered requests don't compose with speculative "
                "decoding (the verify pass has no delta path yet)")
        req._adapter_slot = self.adapters.acquire(name, req.id)

    def _adapter_release(self, req) -> None:
        """Unpin ``req``'s adapter (idempotent — safe on every
        terminal path, and a no-op for base-model requests)."""
        bank = self.adapters
        if bank is not None \
                and getattr(req, "adapter_id", None) is not None:
            bank.release(req.id)

    def _adapter_operands(self, active):
        """Serving override of the decode-chunk adapter hook: when any
        active slot decodes through a bank adapter, return the traced
        operands — the per-slot bank-slot map (-1 = base model) plus
        the bank's device-cached ``[L, S, ...]`` A/B stacks. A pure-
        base batch returns ``(None, None)`` and keeps the fast grouped
        decode program; adapter membership rides the slot map, so the
        compiled-program count never depends on WHICH adapters are
        live (hot load/unload only bumps the bank's device cache)."""
        bank = self.adapters
        if bank is None:
            return None, None
        slots = np.full((self.max_batch,), -1, np.int32)
        any_adaptered = False
        for i in active:
            req = self._slots[i]
            s = getattr(req, "_adapter_slot", None) \
                if req is not None else None
            if s is not None and s >= 0:
                slots[i] = s
                any_adaptered = True
        if not any_adaptered:
            return None, None
        return jnp.asarray(slots), bank.operands(tp=self._gen._tp)

    def _check_overload(self, req: Request) -> None:
        """Admission-time overload shedding (ISSUE 11): reject with a
        typed ``ServerOverloaded`` when (a) the inbox is at its hard
        bound (``FLAGS_serve_inbox_limit``; an unbounded producer can
        no longer grow the waiting list without backpressure), (b) the
        queue depth (inbox + waiting) crossed
        ``FLAGS_serve_shed_queue_depth``, or (c) the PR 9 SLO
        burn-rate gauge crossed ``FLAGS_serve_shed_burn_rate`` (the
        service is already missing its objective — more load only
        deepens the miss). 0 disables each threshold."""
        limit = int(_flag("serve_inbox_limit"))
        depth_cap = int(_flag("serve_shed_queue_depth"))
        with self._inbox_lock:
            inbox = len(self._inbox)
        reason = None
        if limit > 0 and inbox >= limit:
            reason = f"inbox at its bound ({inbox}/{limit})"
        elif depth_cap > 0 and inbox + len(self.waiting) >= depth_cap:
            reason = (f"queue depth {inbox + len(self.waiting)} >= "
                      f"shed threshold {depth_cap}")
        else:
            burn_cap = float(_flag("serve_shed_burn_rate"))
            burn = self.slo_monitor.burn_rate
            if burn_cap > 0 and burn is not None and burn > burn_cap:
                reason = (f"SLO burn rate {burn:.2f} > shed "
                          f"threshold {burn_cap:.2f}")
        if reason is None:
            return
        _stats.inc("serving.shed")
        u = self.usage
        # terminal-state audit (ISSUE 17): a shed-at-submit request
        # DID enter the system — close its (empty) usage record so
        # every request emits exactly one
        rec = u.finish(req, "shed") if u is not None else None
        jr = self.journal
        if jr is not None:
            extra = {"reason": reason}
            if rec is not None:
                extra["usage"] = rec
            jr.record("shed", req.id, -1, extra)
        raise ServerOverloaded(
            f"request {req.id} shed at submit: {reason}")

    @property
    def num_prefilling(self) -> int:
        return len(self._prefilling)

    @property
    def queue_depth(self) -> int:
        """Queued-but-not-yet-admitted requests (inbox + waiting) —
        the fleet router's load/shed signal for this replica."""
        with self._inbox_lock:
            return len(self._inbox) + len(self.waiting)

    @property
    def has_work(self) -> bool:
        """Anything for ``step()`` to do (the fleet replica loop's
        idle test)."""
        return bool(self._inbox or self.waiting or self._prefilling
                    or self.num_active)

    # ---------------- fleet hooks (ISSUE 14) ----------------

    def adopt_request(self, req: Request) -> int:
        """Fleet-tier admission (serving/router.py): enqueue an
        already-constructed request WITHOUT the per-engine overload
        check — the router owns shedding at its tier, and a failover/
        hedge re-dispatch must never bounce off the surviving
        replica's thresholds. The request keeps its original lifecycle
        marks (arrival, TTFT) and any ``_resume_tokens``, so a
        failed-over stream just continues."""
        if len(req.prompt) + req.max_new_tokens > self.max_length:
            raise ValueError("request exceeds engine max_length")
        # re-resolve the adapter against THIS engine's bank: the slot
        # id stamped by the dead replica is meaningless here (acquire
        # is idempotent by rid, so a shared fleet bank just re-pins)
        self._adapter_acquire(req)
        with self._inbox_lock:
            self._inbox.append(req)
        jr = self.journal
        if jr is not None:
            extra = {"prompt_len": int(len(req.prompt)),
                     "max_new": int(req.max_new_tokens),
                     "adopted": True}
            if getattr(req, "tenant", None) is not None:
                extra["tenant"] = req.tenant
            if getattr(req, "adapter_id", None) is not None:
                extra["adapter"] = req.adapter_id
            jr.record("submit", req.id, -1, extra)
        _stats.inc("serve.submitted")
        return req.id

    def detach_inflight(self) -> List[Request]:
        """Crash-failover support (serving/router.py): strip and
        return EVERY in-flight request — inbox, waiting list, prefill
        slots, decode slots — in admission-priority order (queued
        first, then prefilling, then decoding). Pages are deliberately
        NOT freed: this runs against a replica the router already
        declared dead, whose pool (and possibly wedged step) dies with
        it; touching the manager from another thread would race a
        half-finished step. The caller re-dispatches the requests via
        the recompute resume path."""
        with self._inbox_lock:
            inbox, self._inbox = self._inbox, []
        waiting, self.waiting = list(self.waiting), []
        prefilling = [self._prefilling[i].req
                      for i in sorted(self._prefilling)]
        self._prefilling.clear()
        decoding = [r for r in self._slots if r is not None]
        self._slots = [None] * self.max_batch
        self._lens[:] = 0
        self._last_tok[:] = 0
        u = self.usage
        if u is not None:
            # the detached requests stop holding USABLE pages here
            # (the stranded pool dies with the replica): close their
            # page-second integrals so the fleet fold charges them
            # only for time the pages could still serve them
            for r in prefilling + decoding:
                u.set_pages(r, 0)
        out = [r for r in inbox + waiting + prefilling + decoding
               if not r.done]
        # unpin adapters held by this (dead) replica's bank — the
        # adopting replica re-acquires against its own (possibly the
        # same shared) bank, so refcounts never leak across failover
        for r in out:
            self._adapter_release(r)
        return out

    def step(self):
        """One scheduler action: drain admissions (shed-aware), expire
        deadlines, tick the progress watchdog, then run EITHER one
        prefill chunk or one decode chunk per the SLO interleave —
        CRASH-ISOLATED: an exception inside admission or either chunk
        retries with capped exponential backoff and then errors out
        only the offending request (``_recover_*``); the loop keeps
        serving everyone else. Returns requests finished this step.

        Each completed step's wall time is ATTRIBUTED into phase
        histograms via the clock seam (``serve.step.{admit,
        prefill_chunk,decode_chunk,spec_verify}_ms`` plus the
        ``host_overhead_ms`` residual — see ``_observe_step``);
        recovery early-returns skip attribution so the phase sums
        stay an exact partition of the observed ``total_ms``."""
        ts0 = _faults.now()
        self._drain_inbox()
        self._expire_deadlines()
        try:
            self._admit()
        except Exception as e:
            self._recover_admit(e)
        self.slo_monitor.update_gauges(
            len(self.waiting) + len(self._inbox), self.num_active,
            len(self._prefilling), self.max_batch)
        self._watchdog_tick()
        ts_admit = _faults.now()
        action = self._pick_action()
        if action == "prefill":
            self.action_log.append("prefill")
            try:
                out = self._prefill_step()
            except Exception as e:
                return self._recover_prefill(e)
            tgt, self._prefill_active = self._prefill_active, None
            if tgt is not None:
                tgt[0].n_retries = 0  # chunk landed — budget restored
            ts_work = _faults.now()
            u = self.usage
            if u is not None:
                # the chunk prefilled exactly one request: charge it
                # the SAME float the phase histogram observes below —
                # the ledger's conservation invariant is bitwise
                u.charge_phase("prefill_chunk",
                               (ts_work - ts_admit) * 1e3,
                               (tgt[0],) if tgt is not None else ())
            self._observe_step(ts0, ts_admit, ts_work,
                               "prefill_chunk")
            return out
        if self.num_active == 0:
            self._observe_step(ts0, ts_admit, ts_admit, None)
            return []
        self.action_log.append("decode")
        before = [(r, len(r.generated))
                  for r in self._slots if r is not None]
        t0 = time.perf_counter()
        try:
            done = super().step()
        except Exception as e:
            return self._recover_decode(e)
        self._decode_retries = 0
        ts_work = _faults.now()
        dt_ms = (time.perf_counter() - t0) * 1e3
        u = self.usage
        advanced = []
        for req, n0 in before:
            emitted = len(req.generated) - n0
            if emitted <= 0:
                continue
            if u is not None:
                advanced.append(req)
                u.add_tokens(req, decode=emitted)
            # the request waited the whole chunk for its tokens, so
            # its streaming gap is dt_ms/emitted — observed once PER
            # TOKEN, so a slot that finished mid-chunk neither drops
            # out of the histogram nor understates its gap
            gap = dt_ms / emitted
            for _ in range(emitted):
                _stats.observe("serve.tpot_ms", gap)
        phase = ("spec_verify"
                 if getattr(self, "_spec", None) is not None
                 else "decode_chunk")
        if u is not None:
            # the chunk's device time splits over the slots it
            # ADVANCED (a slot the chunk couldn't move shouldn't pay
            # for it); a wholly-stalled chunk splits over everyone
            # who was active when it started — same float as the
            # histogram observation below
            u.charge_phase(phase, (ts_work - ts_admit) * 1e3,
                           advanced or [r for r, _ in before])
        self._observe_step(ts0, ts_admit, ts_work, phase)
        return done

    def _observe_step(self, ts0, ts_admit, ts_work, phase):
        """Per-step serving-time attribution (continuous-telemetry
        tentpole): split the step's wall clock into admit (drain +
        deadline sweep + admission + watchdog), the work phase
        (prefill_chunk / decode_chunk / spec_verify when speculation
        drives decode; migration is timed by the router around slot
        export/import), and host_overhead — the RESIDUAL between the
        work phase's end and step exit (token bookkeeping, tpot
        observes, finish hooks). admit + phase + host_overhead ==
        total EXACTLY per step, so the histograms answer "where did
        the step go" with no unaccounted remainder. All stamps come
        from the clock seam — ManualClock tests see exact values."""
        if not _stats.is_enabled():
            return
        ts_end = _faults.now()
        _stats.observe("serve.step.admit_ms", (ts_admit - ts0) * 1e3)
        if phase is not None:
            _stats.observe("serve.step.%s_ms" % phase,
                           (ts_work - ts_admit) * 1e3)
        _stats.observe("serve.step.host_overhead_ms",
                       (ts_end - ts_work) * 1e3)
        _stats.observe("serve.step.total_ms", (ts_end - ts0) * 1e3)

    def _finish_hook(self, req, slot: int):
        """Serving finish path (called from the engine the moment a
        request completes, before its pages release): stamp t_done,
        observe the lifetime per-token mean, judge the SLO verdict,
        and journal a verdict-rich finish event."""
        req.t_done = _faults.now()
        if getattr(req, "state", None) is None:
            req.state = "ok"
        tpot = getattr(req, "tpot_s", None)
        if tpot is not None:
            # whole-lifetime per-token mean (the chunk-level
            # serve.tpot_ms is the streaming-gap view)
            _stats.observe("serve.request_tpot_ms", tpot * 1e3)
        v = self.slo_monitor.observe_finish(req)
        self._adapter_release(req)
        u = self.usage
        # close the usage record exactly once (a snapshot rides the
        # finish event; the chunk that finished the request may still
        # charge its tail after this — exports read final values)
        rec = u.finish(req, "ok") if u is not None else None
        jr = self.journal
        if jr is not None:
            extra = {"n_tokens": len(req.generated),
                     "ttft_ms": v["ttft_ms"],
                     "tpot_ms": v["tpot_ms"],
                     "slo_ok": v["slo_ok"]}
            if getattr(req, "tenant", None) is not None:
                extra["tenant"] = req.tenant
            if getattr(req, "adapter_id", None) is not None:
                extra["adapter"] = req.adapter_id
            if rec is not None:
                extra["usage"] = rec
            jr.record("finish", req.id, slot, extra)

    # ---------------- failure semantics (ISSUE 11) ----------------

    _FAIL_COUNTERS = {"deadline_exceeded": "serving.deadline_exceeded",
                      "shed": "serving.shed",
                      "error": "serving.request_errors"}

    def _fail_request(self, req: Request, slot: int, state: str,
                      exc: BaseException):
        """Terminal failure path: stamp the request's terminal state
        and error, roll it into the SLO window as a miss, journal the
        terminal event, and move it to ``finished``. The error
        surfaces ONLY to this request (``req.error`` / its caller) —
        never to the serve loop. Callers remove the request from
        queue/slot structures and free its pages FIRST."""
        req.done = True
        req.state = state
        req.error = exc
        req.t_done = _faults.now()
        self.slo_monitor.observe_error(req)
        self._adapter_release(req)
        u = self.usage
        rec = u.finish(req, state) if u is not None else None
        _stats.inc(self._FAIL_COUNTERS.get(
            state, "serving.request_errors"))
        jr = self.journal
        if jr is not None:
            ev = state if state in ("deadline_exceeded", "shed") \
                else "error"
            extra = {"error": type(exc).__name__,
                     "msg": str(exc)[:200]}
            if rec is not None:
                extra["usage"] = rec
            jr.record(ev, req.id, slot, extra)
        self.finished.append(req)

    def _drop_prefill_slot(self, i: int):
        """Vacate prefill slot ``i`` and free its pages (no requeue —
        the caller decides the request's fate)."""
        stt = self._prefilling.pop(i, None)
        if ("prefill", i) in self._mgr._owned:
            self._mgr.free(("prefill", i))
        u = self.usage
        if u is not None and stt is not None:
            u.set_pages(stt.req, 0)

    def _release(self, i: int) -> None:
        """Serving override: close the vacating request's page-second
        integral (the ledger's KV accounting) before the base engine
        frees slot ``i``'s pages."""
        u = self.usage
        if u is not None:
            req = self._slots[i]
            if req is not None:
                u.set_pages(req, 0)
        super()._release(i)

    def _expire_deadlines(self):
        """Abort every request whose ``deadline_ms`` budget elapsed —
        wherever it sits (waiting list, prefill slot, decode slot) —
        freeing its pages and surfacing ``DeadlineExceeded`` only to
        it. Runs once per scheduler step on the injected clock."""
        now = _faults.now()
        expired = [r for r in self.waiting if r.past_deadline(now)]
        for req in expired:
            self.waiting.remove(req)
            self._fail_request(req, -1, "deadline_exceeded",
                               DeadlineExceeded(
                                   f"request {req.id} exceeded its "
                                   f"{req.deadline_ms}ms deadline in "
                                   "queue"))
        for i in [i for i, s in list(self._prefilling.items())
                  if s.req.past_deadline(now)]:
            req = self._prefilling[i].req
            self._drop_prefill_slot(i)
            self._fail_request(req, i, "deadline_exceeded",
                               DeadlineExceeded(
                                   f"request {req.id} exceeded its "
                                   f"{req.deadline_ms}ms deadline "
                                   "during prefill"))
        for i in range(self.max_batch):
            req = self._slots[i]
            if req is not None and req.past_deadline(now):
                self._release(i)
                self._fail_request(req, i, "deadline_exceeded",
                                   DeadlineExceeded(
                                       f"request {req.id} exceeded "
                                       f"its {req.deadline_ms}ms "
                                       "deadline during decode"))

    def _note_retry(self, req, slot: int, exc: BaseException,
                    phase: str) -> bool:
        """Crash-isolation retry bookkeeping: True = a retry is still
        in budget (``FLAGS_serve_step_retries``) and its capped
        exponential backoff has been slept through the serving clock;
        False = the budget is spent and the caller must error the
        request out."""
        budget = int(_flag("serve_step_retries"))
        if req.n_retries >= budget:
            return False
        req.n_retries += 1
        _stats.inc("serving.step_retries")
        u = self.usage
        if u is not None:
            u.add_event(req, retry=1)
        delay_ms = min(
            float(_flag("serve_retry_backoff_ms"))
            * (2 ** (req.n_retries - 1)),
            float(_flag("serve_retry_backoff_cap_ms")))
        jr = self.journal
        if jr is not None:
            jr.record("retry", req.id, slot,
                      {"phase": phase, "attempt": req.n_retries,
                       "backoff_ms": delay_ms,
                       "error": type(exc).__name__})
        _faults.clock().sleep(delay_ms / 1e3)
        return True

    def _recover_admit(self, e: Exception):
        """An exception inside admission: roll back the half-admitted
        request (its prefill-key pages release), then retry-or-fail
        it. Failures outside any admission (no request attributable)
        are not isolable and propagate to ``run()``'s crash dump."""
        if isinstance(e, PoolSizingError):
            raise e
        tgt = self._admitting
        self._admitting = None
        if tgt is None:
            raise e
        req, i = tgt
        self._drop_prefill_slot(i)
        if self._note_retry(req, i, e, "admit"):
            self.waiting.append(req)
            self._sort_waiting()
        else:
            self._fail_request(req, i, "error", e)

    def _recover_prefill(self, e: Exception):
        """An exception inside one slot's prefill chunk: the offending
        request is known (``_prefill_active``); retry it in place with
        backoff, then error out only it. Chunk re-dispatch is clean —
        nothing host-side mutated before the raise, and re-running the
        chunk rewrites the same KV pages with identical values."""
        if isinstance(e, PoolSizingError):
            raise e
        tgt = self._prefill_active
        self._prefill_active = None
        if tgt is None:
            raise e
        req, i = tgt
        if self._note_retry(req, i, e, "prefill"):
            return []
        self._drop_prefill_slot(i)
        if self._slots[i] is req:   # failed past the decode handoff
            self._release(i)
        self._fail_request(req, i, "error", e)
        return []

    def _recover_decode(self, e: Exception):
        """An exception inside the decode chunk: not attributable to
        one slot (the chunk is batched), so retry the whole chunk with
        backoff; once the budget is spent, sacrifice the LEAST-urgent
        active slot (bounded degradation — a persistent fault sheds
        one request per exhausted budget instead of hanging or killing
        the loop) and keep serving."""
        if isinstance(e, PoolSizingError):
            raise e
        budget = int(_flag("serve_step_retries"))
        if self._decode_retries < budget:
            self._decode_retries += 1
            _stats.inc("serving.step_retries")
            delay_ms = min(
                float(_flag("serve_retry_backoff_ms"))
                * (2 ** (self._decode_retries - 1)),
                float(_flag("serve_retry_backoff_cap_ms")))
            jr = self.journal
            if jr is not None:
                jr.record("retry", -1, -1,
                          {"phase": "decode",
                           "attempt": self._decode_retries,
                           "backoff_ms": delay_ms,
                           "error": type(e).__name__})
            _faults.clock().sleep(delay_ms / 1e3)
            return []
        self._decode_retries = 0
        victims = [j for j in range(self.max_batch)
                   if self._slots[j] is not None]
        if not victims:
            raise e
        j = max(victims, key=lambda j: self._urgency(self._slots[j]))
        req = self._slots[j]
        self._release(j)
        self._fail_request(req, j, "error", e)
        return []

    def _watchdog_tick(self):
        """Progress watchdog: a request whose token progress marker
        hasn't moved for ``FLAGS_serve_watchdog_steps`` scheduler
        steps is preempted/requeued (first trip) and failed (second) —
        the loop never hangs behind a wedged slot. 0 disables."""
        n = int(_flag("serve_watchdog_steps"))
        if n <= 0:
            return
        for i, stt in list(self._prefilling.items()):
            self._wd_check(stt.req, ("prefill", stt.pos), i, n, True)
        for i in range(self.max_batch):
            req = self._slots[i]
            if req is not None:
                self._wd_check(req, ("decode", len(req.generated)),
                               i, n, False)

    def _wd_check(self, req, mark, slot: int, n: int,
                  prefilling: bool):
        if req._wd_mark != mark:
            req._wd_mark = mark
            req._wd_steps = 0
            return
        req._wd_steps += 1
        if req._wd_steps < n:
            return
        req._wd_steps = 0
        req._wd_mark = None
        req._wd_trips += 1
        jr = self.journal
        if jr is not None:
            jr.record("watchdog", req.id, slot,
                      {"trip": req._wd_trips,
                       "phase": "prefill" if prefilling else "decode"})
        if req._wd_trips <= 1:
            # first trip: give the stack one recovery shot — requeue
            # (prefill) / preempt-by-recompute (decode); re-admission
            # is prefix-cache-hot, so a transient wedge costs little
            _stats.inc("serving.watchdog_preempts")
            if prefilling:
                self._requeue_prefill(slot)
            else:
                self._preempt_slot(slot)
            return
        _stats.inc("serving.watchdog_kills")
        if prefilling:
            self._drop_prefill_slot(slot)
        else:
            self._release(slot)
        self._fail_request(req, slot, "error", WatchdogTimeout(
            f"request {req.id}: no token progress for {n} scheduler "
            "steps twice (one preempt/requeue already spent)"))

    def run(self):
        """Drain: step until every submitted request finishes.

        Crash-dump-on-exception: any raise journals an ``error``
        event and writes the flight-recorder tail + stats snapshot +
        every still-in-flight request to a JSONL artifact
        (``crash_dump``) before propagating. On every exit the
        ``serving.unserved`` counter stamps requests that never
        reached admission (their queue wait is otherwise invisible —
        ``serve.queue_wait_ms`` only observes admitted requests)."""
        try:
            while (self._inbox or self.waiting or self._prefilling
                   or self.num_active):
                self.step()
        except BaseException as e:
            jr = self.journal
            if jr is not None:
                jr.record("error", -1, -1,
                          {"error": type(e).__name__})
            self.crash_dump(error=e)
            raise
        finally:
            unserved = len(self._inbox) + len(self.waiting)
            if unserved:
                _stats.inc("serving.unserved", unserved)
                u = self.usage
                if u is not None:
                    # terminal-state audit: never-admitted requests
                    # still emit exactly one usage record each
                    for req in list(self._inbox) + list(self.waiting):
                        u.finish(req, "unserved")
            if self.journal is not None:
                self.journal.publish_gauges()
        return self.finished

    def crash_dump(self, error=None,
                   path: Optional[str] = None) -> Optional[str]:
        """Post-mortem JSONL artifact: every surviving journal event
        (``type=event`` lines), the full ``stats.snapshot()``
        (``type=stats``), and a ``type=crash`` header naming the error
        and every request still in flight — inbox/waiting requests
        (the unserved ones), prefilling slots with their chunk
        position, and active decode slots. Written under
        ``FLAGS_serve_journal_dir`` (default: the system temp dir) as
        ``serve_crash_rank<r>_pid<pid>.jsonl``; read it back with
        ``tools/serve_top.py``.

        NEVER RAISES (ISSUE 11): this runs inside ``run()``'s error
        handling, and a failed dump (full disk, bad journal dir, an
        injected ``journal.dump`` fault) must not mask the original
        exception. On failure it warns on stderr and returns None."""
        import sys

        try:
            return self._crash_dump_impl(error, path)
        except BaseException as dump_err:  # noqa: BLE001 — by design
            print(f"serve: crash dump FAILED ({dump_err!r}) — "
                  "original error preserved", file=sys.stderr)
            return None

    def _crash_dump_impl(self, error, path: Optional[str]) -> str:
        import json
        import os
        import sys
        import tempfile

        f0 = self.faults
        if f0 is not None:
            f0.fire("journal.dump")
        if path is None:
            d = str(_flag("serve_journal_dir")) or tempfile.gettempdir()
            rank = 0
            try:
                import jax

                rank = int(jax.process_index())
            except Exception:
                pass
            path = os.path.join(
                d, f"serve_crash_rank{rank}_pid{os.getpid()}.jsonl")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        unserved = []
        with self._inbox_lock:
            inbox = list(self._inbox)
        for req in inbox:
            unserved.append({"rid": req.id, "state": "inbox",
                             "prompt_len": int(len(req.prompt))})
        for req in self.waiting:
            unserved.append({"rid": req.id, "state": "waiting",
                             "prompt_len": int(len(req.prompt))})
        for i, stt in sorted(self._prefilling.items()):
            unserved.append({"rid": stt.req.id, "state": "prefilling",
                             "slot": i, "pos": int(stt.pos),
                             "prompt_len": int(len(stt.tokens))})
        for i, req in enumerate(self._slots):
            if req is not None:
                unserved.append({"rid": req.id, "state": "decoding",
                                 "slot": i,
                                 "n_tokens": len(req.generated)})
        events = self.journal.events() if self.journal is not None \
            else []
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps({"type": "event", **ev}) + "\n")
            f.write(json.dumps({"type": "stats",
                                "stats": _stats.snapshot()}) + "\n")
            f.write(json.dumps({
                "type": "crash",
                "error": repr(error) if error is not None else None,
                "unserved": unserved,
                "dropped_events": (self.journal.dropped
                                   if self.journal is not None
                                   else 0)}) + "\n")
        self.last_crash_dump = path
        print(f"serve: crash dump -> {path}", file=sys.stderr)
        return path

    # ---------------- admission ----------------

    def _drain_inbox(self):
        """Move submitted requests into the priority-ordered waiting
        list — SHED-AWARE (ISSUE 11): once the sorted queue is past
        ``FLAGS_serve_shed_queue_depth``, the overflow tail (lowest
        priority, newest arrivals) terminates in the ``shed`` state
        instead of growing the waiting list without bound. The
        submit-side check already rejects most overload; this is the
        backstop for racing producers that got past it."""
        with self._inbox_lock:
            newly, self._inbox = self._inbox, []
        for req in newly:
            req._seq = next(self._arrival)
            self.waiting.append(req)
        if newly:
            jr = self.journal
            if jr is not None:
                for req in newly:
                    jr.record("queued", req.id, -1, None)
            self._sort_waiting()
            cap = int(_flag("serve_shed_queue_depth"))
            if cap > 0 and len(self.waiting) > cap:
                overflow = self.waiting[cap:]
                del self.waiting[cap:]
                for req in overflow:
                    self._fail_request(
                        req, -1, "shed", ServerOverloaded(
                            f"request {req.id} shed at drain: queue "
                            f"depth past {cap}"))

    def _sort_waiting(self):
        # higher priority first; within a level, STABLE adapter
        # grouping (ISSUE 18): requests sharing an adapter sort
        # adjacently, groups ordered by their oldest member's arrival
        # and FIFO inside each group — same-adapter requests admit
        # together so a decode chunk carries fewer distinct adapters
        # (tighter ragged delta groups). With no adapters every
        # request shares the None group and this is EXACTLY the old
        # priority-FIFO order. The skip-ahead window scans THIS order.
        first: Dict[Optional[str], int] = {}
        for r in self.waiting:
            a = getattr(r, "adapter_id", None)
            s = getattr(r, "_seq", r.id)
            if a not in first or s < first[a]:
                first[a] = s
        self.waiting.sort(
            key=lambda r: (-getattr(r, "priority", 0),
                           first[getattr(r, "adapter_id", None)],
                           getattr(r, "_seq", r.id)))

    @staticmethod
    def _tenant_of(req) -> str:
        t = getattr(req, "tenant", None)
        return t if t is not None else "default"

    @staticmethod
    def _admit_cost(req) -> int:
        """DWRR cost of admitting ``req``, in tokens: the prompt it
        will prefill plus the generation budget it may decode — a
        work proxy known BEFORE the request runs."""
        return int(len(req.prompt)) + int(req.max_new_tokens)

    def _pick_waiting(self):
        """Admission pick. Default: the engine's priority-FIFO bounded
        skip-ahead. With ``SLOConfig.tenant_fair``: DEFICIT-WEIGHTED
        round-robin over per-tenant queues — each pick credits every
        waiting tenant ``fair_quantum * weight`` deficit tokens, the
        richest tenant's first admissible request (within the
        skip-ahead window of its own queue) admits and pays its token
        cost. A flooding tenant drains its deficit as fast as it
        earns it, so light tenants accumulate credit and interleave
        at their weighted share. The engine's starvation bound is
        PRESERVED: every pass-over of an earlier arrival bumps its
        ``_admit_skips``, and a head skipped ``starvation_bound``
        times admits next regardless of deficits."""
        if not self.slo.tenant_fair:
            return super()._pick_waiting()
        if not self.waiting:
            return None
        head = self.waiting[0]
        if head._admit_skips >= self.starvation_bound:
            # bounded unfairness: the window collapses to the head
            return self.waiting.pop(0) if self._can_admit(head) \
                else None
        queues: Dict[str, List[Request]] = {}
        for r in self.waiting:
            queues.setdefault(self._tenant_of(r), []).append(r)
        d = self._fair_deficit
        for t in list(d):
            if t not in queues:   # vanished tenant banks no credit
                del d[t]
        w = self.slo.tenant_weights
        for t in queues:
            d[t] = d.get(t, 0.0) \
                + self.slo.fair_quantum * float(w.get(t, 1.0))
        for t in sorted(queues, key=lambda q: (-d[q], q)):
            for r in queues[t][: self.admit_window]:
                if self._can_admit(r):
                    d[t] -= self._admit_cost(r)
                    j = self.waiting.index(r)
                    if j > 0:
                        for skipped in self.waiting[:j]:
                            skipped._admit_skips += 1
                        _stats.inc("serving.admission_skips", j)
                    return self.waiting.pop(j)
        return None

    def _slot_free(self, i: int) -> bool:
        return self._slots[i] is None and i not in self._prefilling

    @staticmethod
    def _admit_tokens(req):
        """What admission will prefill: the prompt, or the recorded
        prompt+generated resume stream of a preempted request."""
        toks = getattr(req, "_resume_tokens", None)
        return req.prompt if toks is None else toks

    def _first_chunk_pages(self, req) -> int:
        """Pages the FIRST prefill chunk needs beyond any prefix hit."""
        toks = self._admit_tokens(req)
        shared = self.prefix_cache.match(toks) \
            if self.prefix_cache is not None else []
        covered = len(shared) * self.page_size
        c = self._chunk_size(len(toks) - covered)
        need = min(self._mgr.pages_needed(covered + c),
                   self._pages_per_seq)
        return need - len(shared)

    def _restore_prefix(self, req) -> int:
        """Host-tier promotion ahead of admission (ISSUE 20): pull the
        spilled continuation of this request's chain back into free
        pool pages, so the ``match`` below sees it as an ordinary
        prefix hit and the suffix prefill shrinks by the restored
        coverage. Reserves the first chunk's worth of pages so a
        restore can never starve the very admission it serves."""
        ht = self.host_tier
        if ht is None or not len(ht):
            return 0
        toks = self._admit_tokens(req)
        reserve = self._mgr.pages_needed(
            self._chunk_size(len(toks))) + 1
        restored = self.prefix_cache.restore_chain(toks,
                                                   reserve=reserve)
        if restored:
            _stats.inc("serving.prefix_restored_pages", restored)
        return restored

    def _can_admit(self, req) -> bool:
        self._restore_prefix(req)
        need = self._first_chunk_pages(req)
        # pool pressure: evict cold cached prefixes page by page (an
        # evicted entry only frees its page if no live sequence still
        # maps it, so re-check after each drop)
        while need > self._mgr.free_pages \
                and self.prefix_cache is not None \
                and self.prefix_cache.evict(1):
            # eviction can drop the very pages the match above counted
            # as covered, so recompute — the admit decision must
            # reflect the post-eviction cache. match() LRU-touches its
            # chain, so the matched prefix is the LAST thing evicted.
            need = self._first_chunk_pages(req)
        return need <= self._mgr.free_pages

    def _evict_for(self, n_pages: int) -> bool:
        """Free pool pages for an n_pages grow by dropping cold cached
        prefixes; True once the free list covers it."""
        if self.prefix_cache is not None:
            while n_pages > self._mgr.free_pages \
                    and self.prefix_cache.evict(1):
                pass
        return n_pages <= self._mgr.free_pages

    def _admit_into(self, req: Request, i: int):
        """Park ``req`` on slot ``i`` in the chunk-prefill phase: map
        any cached prefix pages, allocate the first chunk's tail pages,
        and let ``_prefill_step`` fill the prompt chunk by chunk. No
        prefill compute happens at admission — admitting a 4k prompt
        costs a page-table update, not a 4k-token program."""
        self._admitting = (req, i)   # crash-isolation attribution
        now = _faults.now()
        u = self.usage
        if req.t_admitted is None:
            # first admission only — a preempted/requeued request
            # keeps its original marks (queue-wait and TTFT measure
            # the user-visible wait, and the on_token wrapper is
            # already installed)
            req.t_admitted = now
            arrival = getattr(req, "arrival_time", now)
            _stats.observe("serve.queue_wait_ms",
                           (now - arrival) * 1e3)
            _stats.inc("serving.admitted")
            if u is not None:
                u.note_queue(req, now - arrival)
            self._hook_first_token(req)
        toks = self._admit_tokens(req)
        shared = []
        if self.prefix_cache is not None:
            shared = self.prefix_cache.match(toks)
            if shared:
                _stats.inc("serving.prefix_hit")
                _stats.inc("serving.prefix_pages_saved", len(shared))
            else:
                _stats.inc("serving.prefix_miss")
        jr = self.journal
        if jr is not None:
            jr.record("admitted", req.id, i,
                      {"prefix_pages": len(shared),
                       "resume": getattr(req, "_resume_tokens", None)
                       is not None})
        key = ("prefill", i)
        if shared:
            self._mgr.share(key, shared)
            if u is not None:
                # shared pages charge EACH holder from its own map
                # time — the sharer starts paying page-seconds now —
                # and the pages it did NOT have to prefill are a
                # credit (the prefix-cache's own refs charge nobody)
                u.credit_prefix(req, len(shared))
                u.set_pages(req, len(shared), now=now)
        self._prefilling[i] = _Prefill(
            req, pos=len(shared) * self.page_size, tokens=toks)
        self._admitting = None

    def _hook_first_token(self, req):
        """Wrap the user's on_token with the TTFT stamp (fires exactly
        once, on the first emitted token)."""
        user_cb = getattr(req, "on_token", None)

        def cb(r, t, _u=user_cb):
            if getattr(r, "t_first_token", None) is None:
                r.t_first_token = _faults.now()
                ttft_ms = (r.t_first_token
                           - getattr(r, "arrival_time",
                                     r.t_first_token)) * 1e3
                _stats.observe("serve.ttft_ms", ttft_ms)
                jr = self.journal
                if jr is not None:
                    jr.record("first_token", r.id, -1,
                              {"ttft_ms": round(ttft_ms, 3)})
            if _u is not None:
                _u(r, t)

        req.on_token = cb

    # ---------------- scheduling ----------------

    def _pick_action(self) -> str:
        """Prefill vs decode for this step: the weighted interleave
        cycle, active only under CONTENTION (both phases have work).
        The cycle restarts whenever contention (re)starts, so while any
        request is decode-ready at most ``prefill_burst`` consecutive
        prefill chunks ever run — the stall bound."""
        if not self._prefilling:
            self._cycle_pos = 0
            return "decode"
        if self.num_active == 0:
            self._cycle_pos = 0
            return "prefill"
        cycle = self.slo.prefill_burst + self.slo.decode_burst
        pos = self._cycle_pos % cycle
        self._cycle_pos += 1
        return "prefill" if pos < self.slo.prefill_burst else "decode"

    def _chunk_size(self, remaining: int) -> int:
        """Chunk length for ``remaining`` prompt tokens: full chunks
        while they last, the tail bucket-padded (one compiled program
        per SIZE — prompt_bucket bounds the tail-program count)."""
        if remaining >= self.slo.prefill_chunk:
            return self.slo.prefill_chunk
        bs = self.prompt_bucket
        return max(min(-(-remaining // bs) * bs,
                       self.slo.prefill_chunk), 1)

    def _chunk_floor(self) -> int:
        """Smallest chunk graceful degradation may shrink to: one
        page/bucket of tokens (whichever is smaller — shrunk sizes
        stay multiples of it, bounding the per-size compile count to
        the halving chain)."""
        return max(1, min(self.prompt_bucket, self.page_size))

    def _shrunk_chunk(self, c: int) -> int:
        """Next smaller chunk size in the degradation chain: half of
        ``c``, rounded up to the floor's multiple, strictly below
        ``c``."""
        floor = self._chunk_floor()
        nxt = -(-(c // 2) // floor) * floor
        return max(min(nxt, c - 1), floor)

    def _postprocess_tokens(self, toks_np, active):
        """Serving override of the decode-chunk token filter (ISSUE
        11): route the chunk's token matrix through any scheduled
        ``decode.step`` corruption, then validate the whole ACTIVE
        block before a single request mutates — a detected corruption
        raises :class:`TokenCorruption` while the crash-isolated retry
        is still clean (re-running the chunk rewrites the same KV
        pages with identical values)."""
        f = self._faults
        if f is not None and active:
            i0 = active[0]
            cur = int(toks_np[i0, 0])
            poked = f.corrupt("decode.step", cur)
            if poked != cur:
                # np.asarray over a jax array is a read-only view —
                # corrupt a writable copy (the fault path only)
                toks_np = np.array(toks_np)
                toks_np[i0, 0] = poked
        v = self.model.vocab_size
        blk = toks_np[active]
        if blk.size and (int(blk.min()) < 0 or int(blk.max()) >= v):
            raise TokenCorruption(
                f"decode chunk produced token(s) outside [0, {v}) "
                f"for slots {active}")
        return toks_np

    def _urgency(self, req):
        """Sort key: most urgent first (priority, then admission order
        — finish what started first)."""
        return (-getattr(req, "priority", 0), req.t_admitted)

    def _pick_prefilling(self) -> int:
        """Most urgent prefilling slot: priority, then admission
        order (finish what started first — chunks of one prompt don't
        interleave with another's without cause)."""
        return min(self._prefilling,
                   key=lambda i: self._urgency(self._prefilling[i].req))

    def _chunk_rung(self, c: int, adaptered: bool = False) -> str:
        """Rung name of the c-token chunk program —
        ``serve.prefill[c=N,mp=M]`` under tensor parallelism; the
        multi-LoRA variant reports as ``serve.prefill.lora[...]``."""
        tp = self._gen._tp
        mp = f",mp={tp.mp}" if tp is not None else ""
        tag = "serve.prefill.lora" if adaptered else "serve.prefill"
        return f"{tag}[c={c}{mp}]"

    def _get_chunk_prefill(self, c: int, adaptered: bool = False):
        """One compiled chunk program per (chunk SIZE, adaptered):
        start/len are traced operands — every chunk of every request
        shares it — and the adapter operands (slot map + banks) are
        traced too, so adapter membership and hot load/unload never
        add programs (at most 2 per chunk size)."""
        key = (c, adaptered)
        if key not in self._chunk_jit:
            import functools

            import jax

            self._chunk_jit[key] = _roofline.AotProgram(
                self._chunk_rung(c, adaptered),
                jax.jit(self._chunk_prefill_fn, donate_argnums=(8, 9)))
        return self._chunk_jit[key]

    def _chunk_prefill_fn(self, weights, embed, head_t, lnf_s, lnf_b,
                          ids, start, chunk_len, ck, cv, tables,
                          adapter_slots=None, adapter_banks=None):
        """Compiled chunk program: prefill ``ids`` at positions
        ``start..`` against the cached prefix + in-chunk causal
        triangle, returning the last VALID position's logits (used only
        by the final chunk — one [1, d] @ [d, vocab] head matmul per
        chunk buys an honest per-chunk device sync). With adapter
        operands set, every projection adds its ragged grouped LoRA
        delta (one launch per projection per layer)."""
        g = self._gen
        st = self.model.stack
        adapters = None
        if adapter_banks is not None:
            adapters = dict(adapter_banks)
            adapters["slots"] = adapter_slots
        x = embed[ids].astype(g._cdtype)
        h, cache = st.prefill_chunk_raw(
            weights, x, PagedKV(ck, cv), tables, start, chunk_len,
            g._cos, g._sin, a8w8=g._a8w8, tp=g._tp, adapters=adapters)
        hl = h[jnp.arange(h.shape[0]), chunk_len - 1]
        logits = g._logits(hl, head_t, lnf_s, lnf_b)
        return logits, cache.k, cache.v

    def _prefill_step(self):
        """Run ONE prefill chunk for the most urgent prefilling slot;
        on prompt completion the request joins the decode batch with
        its first token emitted. Returns requests finished this step
        (a one-token request can finish straight out of prefill)."""
        i = self._pick_prefilling()
        stt = self._prefilling[i]
        req = stt.req
        self._prefill_active = (req, i)  # crash-isolation attribution
        toks = stt.tokens
        L = len(toks)
        c = self._chunk_size(L - stt.pos)
        n = min(L - stt.pos, c)
        key = ("prefill", i)
        need = min(self._mgr.pages_needed(stt.pos + c),
                   self._pages_per_seq)
        have = len(self._mgr._owned.get(key, ()))
        if need > have and not self._evict_for(need - have):
            # graceful degradation FIRST (ISSUE 11): shrink this
            # step's chunk until its tail pages fit the squeezed pool
            # — smaller chunks keep tokens flowing where the full
            # chunk would stall, requeue, or shed
            if _flag("serve_chunk_shrink"):
                c2 = c
                while c2 > self._chunk_floor():
                    c2 = self._shrunk_chunk(c2)
                    need2 = min(self._mgr.pages_needed(stt.pos + c2),
                                self._pages_per_seq)
                    if need2 <= have \
                            or self._evict_for(need2 - have):
                        _stats.inc("serving.chunk_shrinks")
                        c, n = c2, min(L - stt.pos, c2)
                        need = need2
                        break
        if need > have and not self._evict_for(need - have):
            # pool exhausted even after dropping every cold cached
            # prefix (admission only reserved the FIRST chunk's pages,
            # so later chunks can outgrow the pool under load)
            if self.num_active > 0:
                # decoders hold the pages and free them as they
                # finish — defer this chunk, the interleave cycle
                # keeps decode draining meanwhile
                _stats.inc("serving.prefill_stalls")
                jr = self._journal
                if jr is not None:
                    jr.record("stall", req.id, i,
                              {"need": need - have})
                return []
            # no decoders to wait for: requeue LESS-urgent prefilling
            # requests (never this one — ``i`` is the most urgent, and
            # sacrificing it would livelock: it re-admits first and
            # starves the survivor all over again) until this chunk's
            # pages fit
            while len(self._prefilling) > 1 \
                    and not self._evict_for(need - have):
                victim = max(
                    (j for j in self._prefilling if j != i),
                    key=lambda j: self._urgency(
                        self._prefilling[j].req))
                self._requeue_prefill(victim)
            if not self._evict_for(need - have):
                raise PoolSizingError(
                    f"request {req.id} needs {need} KV pages but the "
                    f"pool can only ever provide "
                    f"{self._mgr.free_pages + have} "
                    f"(num_pages={self._mgr.num_pages}); increase "
                    f"num_pages or cap prompt/generation length")
        if need > have:
            self._mgr.grow(key, need - have)
            u = self.usage
            if u is not None:
                u.set_pages(req, len(self._mgr._owned[key]))
        fi = self.faults
        if fi is not None:
            fi.fire("prefill.dispatch", rid=req.id)
        tables = self._mgr.block_tables([key], self._pages_per_seq)
        ids = np.zeros((1, c), np.int32)
        ids[0, :n] = toks[stt.pos: stt.pos + n]
        self._gen._count_a8w8(1)
        lnf_s, lnf_b = self._gen._lnf()
        a_slot = getattr(req, "_adapter_slot", None)
        adaptered = self.adapters is not None and a_slot is not None \
            and a_slot >= 0
        extra = ()
        if adaptered:
            extra = (jnp.asarray([a_slot], jnp.int32),
                     self.adapters.operands(tp=self._gen._tp))
            _stats.inc("lora.grouped_launches",
                       4 * self.model.stack.num_layers)
        t0 = time.perf_counter()
        logits, self._ck, self._cv = self._get_chunk_prefill(
            c, adaptered)(
            self._gen._weights(), self._gen._embed(),
            self._gen._head_t, lnf_s, lnf_b, jnp.asarray(ids),
            jnp.asarray([stt.pos], jnp.int32),
            jnp.asarray([n], jnp.int32), self._ck, self._cv, tables,
            *extra)
        tok = int(np.asarray(
            self._gen._argmax(jnp.asarray(logits)))[0])
        if fi is not None:
            tok = fi.corrupt("prefill.dispatch", tok)
        if not 0 <= tok < self.model.vocab_size:
            # corrupt-and-DETECT: the poisoned token never reaches the
            # request's stream; the raise happens before any host-side
            # mutation, so the crash-isolated retry re-runs this chunk
            # cleanly (same KV pages rewritten with identical values)
            raise TokenCorruption(
                f"prefill chunk for request {req.id} produced token "
                f"{tok} outside [0, {self.model.vocab_size})")
        # the argmax fetch synced the chunk — honest phase roofline
        _roofline.analyze(self._chunk_rung(c, adaptered),
                          time.perf_counter() - t0)
        _stats.inc("serve.prefill_chunks")
        _stats.inc("serve.prefill_tokens", n)
        u = self.usage
        if u is not None:
            u.add_tokens(req, prefill=n)
        stt.pos += n
        jr = self._journal
        if jr is not None:
            jr.record("prefill_chunk", req.id, i,
                      {"c": c, "pos": stt.pos, "n": n})
        if stt.pos < L:
            return []
        # prompt complete: emit the next token, join the decode batch
        del self._prefilling[i]
        self._mgr.rekey(key, ("slot", i))
        if self.prefix_cache is not None:
            try:
                self.prefix_cache.insert(
                    toks, self._mgr._owned[("slot", i)])
            except Exception:
                # a prefix-cache registration failure (e.g. an
                # injected prefix.insert fault) costs future page
                # reuse, never the request — absorbed here, counted,
                # and the request proceeds to decode untouched
                _stats.inc("serving.prefix_insert_errors")
        self._slots[i] = req
        req.generated.append(tok)
        if u is not None:
            # the final chunk's logits emitted the stream's first
            # token — a generated (decode-side) token in the ledger
            u.add_tokens(req, decode=1)
        cb = getattr(req, "on_token", None)
        if cb is not None:
            cb(req, tok)
        if (req.eos_token_id is not None and tok == req.eos_token_id) \
                or len(req.generated) >= req.max_new_tokens:
            req.done = True
            self._finish_hook(req, i)
            self._release(i)
            self.finished.append(req)
            return [req]
        if jr is not None:
            jr.record("decode", req.id, i, None)
        self._lens[i] = L + 1
        self._last_tok[i] = tok
        return []

    # ---------------- pool-pressure recovery ----------------

    def _requeue_prefill(self, i: int):
        """Abort slot ``i``'s chunk prefill back to the waiting list,
        freeing its pages (its _resume_tokens, if any, survive so a
        preempted request still resumes mid-stream). Progress is kept
        by the surviving prefilling slots, which can now grow."""
        stt = self._prefilling.pop(i)
        self._mgr.free(("prefill", i))
        _stats.inc("serving.prefill_requeues")
        req = stt.req
        req.n_requeues = getattr(req, "n_requeues", 0) + 1
        u = self.usage
        if u is not None:
            u.set_pages(req, 0)
            u.add_event(req, requeue=1)
        jr = self.journal
        if jr is not None:
            jr.record("requeue", req.id, i, {"pos": int(stt.pos)})
        self.waiting.append(req)
        self._sort_waiting()
        if jr is not None:
            jr.record("queued", req.id, -1, None)
        return []

    def _preempt_slot(self, j: int):
        """Preempt decode slot ``j`` by recomputation (vLLM-style):
        free its pages and requeue the request with prompt+generated
        as its resume stream — re-admission chunk-prefills the whole
        history (usually prefix-cache-hot) and the final chunk emits
        the NEXT token, so the user-visible stream just continues."""
        req = self._slots[j]
        req._resume_tokens = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)])
        self._park_preempted_kv(j, req._resume_tokens)
        self._release(j)   # the override closes the page integral
        _stats.inc("serving.preemptions")
        req.n_preempts = getattr(req, "n_preempts", 0) + 1
        u = self.usage
        if u is not None:
            u.add_event(req, preempt=1)
        jr = self.journal
        if jr is not None:
            jr.record("preempt", req.id, j,
                      {"n_generated": len(req.generated)})
        self.waiting.append(req)
        self._sort_waiting()
        if jr is not None:
            jr.record("queued", req.id, -1, None)

    def _park_preempted_kv(self, j: int, resume_toks) -> None:
        """Keep a preempted slot's COMPLETE KV pages reachable instead
        of dropping them (ISSUE 20): register them in the prefix cache
        under the resume stream's content chain before the release.
        Under continued pressure they are exactly the coldest entries
        ``_evict_for`` evicts next — which, with a host tier, demotes
        them to host DRAM — so re-admission restores pages and
        re-prefills only the tail, and full recompute becomes the last
        resort. Only positions strictly below ``lens-1`` are certainly
        written between steps, hence the (lens-1)//page_size bound."""
        if self.prefix_cache is None:
            return
        n_full = min((int(self._lens[j]) - 1) // self.page_size,
                     len(resume_toks) // self.page_size)
        if n_full <= 0:
            return
        pages = self._mgr._owned.get(("slot", j), [])[:n_full]
        if not pages:
            return
        try:
            self.prefix_cache.insert(resume_toks, pages)
        except Exception:
            # registration is an optimization; an injected
            # prefix.insert fault must never break the preemption
            _stats.inc("serving.prefix_insert_errors")

    def _grow_decode_slot(self, i: int, n_pages: int) -> bool:
        """Serving override of the decode-time grow: under pool
        pressure evict cold cached prefixes first; if the pool is
        STILL exhausted, preempt the LEAST-urgent active slot (freeing
        its pages may also unpin cached prefixes, so re-evict each
        round) until slot ``i`` fits or is itself the victim."""
        while not self._evict_for(n_pages):
            victim = max(
                (j for j in range(self.max_batch)
                 if self._slots[j] is not None),
                key=lambda j: self._urgency(self._slots[j]))
            self._preempt_slot(victim)
            if victim == i:
                return False
        self._mgr.grow(("slot", i), n_pages)
        u = self.usage
        if u is not None and self._slots[i] is not None:
            u.set_pages(self._slots[i],
                        len(self._mgr._owned[("slot", i)]))
        return True
