"""SLO goodput monitor: per-request verdicts + rolling service health.

Consumes the request-lifecycle stream online (the scheduler feeds it
each finished request as its ``finish`` journal event is recorded) and
turns the raw TTFT/TPOT readings into service-level accounting:

- **per-request verdict**: TTFT and TPOT each judged against the
  ``SLOConfig`` targets (``ttft_target_ms`` / ``tpot_target_ms``); a
  request with no TPOT reading (single-token generations) passes that
  leg vacuously. The verdict is stamped back onto the request
  (``req.slo_ok``) and into the journal's ``finish`` event, so offline
  tools never re-derive it.
- **rolling goodput** (``slo.goodput`` gauge): fraction of the last
  ``slo_window`` finished requests meeting BOTH targets — the number
  the serve bench reports as ``serve_goodput`` and
  ``tools/bench_gate.py`` gates (direction "down").
- **burn rate** (``slo.burn_rate`` gauge): SRE-style error-budget
  burn over the same window — ``(1 - goodput) / (1 - objective)``;
  1.0 means the miss rate exactly consumes the budget implied by
  ``goodput_objective``, >1 means the budget is burning down.
- **load gauges**: ``slo.queue_depth`` (inbox + waiting) and
  ``slo.slot_occupancy`` ((decoding + prefilling) / max_batch),
  refreshed by the scheduler every step — the live dashboard's
  (``tools/serve_top.py``) pressure row.

Counters: ``slo.{finished,ok,ttft_miss,tpot_miss,errors}`` (errors =
requests that ended in a failure terminal state — deadline, shed,
step error — each rolled into the goodput window as a miss).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from ..profiler import stats as _stats

__all__ = ["SLOMonitor"]


class SLOMonitor:
    """Online TTFT/TPOT verdicts + rolling goodput/burn-rate gauges."""

    def __init__(self, ttft_target_ms: Optional[float] = 1000.0,
                 tpot_target_ms: Optional[float] = 100.0,
                 objective: float = 0.99, window: int = 256,
                 tenant_windows_max: Optional[int] = None):
        self.ttft_target_ms = ttft_target_ms
        self.tpot_target_ms = tpot_target_ms
        if not 0.0 < float(objective) < 1.0:
            raise ValueError("goodput objective must be in (0, 1)")
        self.objective = float(objective)
        self._window: deque = deque(maxlen=max(int(window), 1))
        # per-tenant rolling windows (ISSUE 17): created lazily on
        # the first finish carrying a non-None req.tenant, bounded by
        # tenant_windows_max (overflow tenants share "__other__") —
        # the no-tenant default path never allocates any of this
        if tenant_windows_max is None:
            from ..core.flags import flag as _flag
            tenant_windows_max = int(_flag("usage_tenants_max"))
        self.tenant_windows_max = max(int(tenant_windows_max), 1)
        self._tenant_windows: dict = {}
        self._lock = threading.Lock()

    # ---------------- verdicts ----------------

    def verdict(self, ttft_ms: Optional[float],
                tpot_ms: Optional[float]):
        """(ttft_ok, tpot_ok) against the targets; a missing reading
        or a disabled (None) target passes that leg vacuously."""
        ttft_ok = (ttft_ms is None or self.ttft_target_ms is None
                   or ttft_ms <= self.ttft_target_ms)
        tpot_ok = (tpot_ms is None or self.tpot_target_ms is None
                   or tpot_ms <= self.tpot_target_ms)
        return ttft_ok, tpot_ok

    def observe_finish(self, req) -> dict:
        """Judge one finished request, roll the goodput window, and
        publish the ``slo.*`` metrics. Stamps ``req.slo_ok`` and
        returns the verdict dict the journal's finish event records."""
        ttft = getattr(req, "ttft_s", None)
        tpot = getattr(req, "tpot_s", None)
        ttft_ms = None if ttft is None else ttft * 1e3
        tpot_ms = None if tpot is None else tpot * 1e3
        ttft_ok, tpot_ok = self.verdict(ttft_ms, tpot_ms)
        ok = ttft_ok and tpot_ok
        with self._lock:
            self._window.append(ok)
            good = sum(self._window) / len(self._window)
            self._roll_tenant(req, ok)
        _stats.inc("slo.finished")
        if ok:
            _stats.inc("slo.ok")
        if not ttft_ok:
            _stats.inc("slo.ttft_miss")
        if not tpot_ok:
            _stats.inc("slo.tpot_miss")
        _stats.set_gauge("slo.goodput", round(good, 4))
        _stats.set_gauge("slo.burn_rate", round(self._burn(good), 3))
        req.slo_ok = ok
        return {"ttft_ms": None if ttft_ms is None
                else round(ttft_ms, 3),
                "tpot_ms": None if tpot_ms is None
                else round(tpot_ms, 3),
                "ttft_ok": ttft_ok, "tpot_ok": tpot_ok, "slo_ok": ok}

    def observe_error(self, req) -> None:
        """Roll a FAILED request (deadline/shed/step error, ISSUE 11)
        into the goodput window as a miss: a request the service
        dropped is by definition not good throughput, whatever its
        latencies were before it died. Stamps ``req.slo_ok = False``
        and publishes the same rolling gauges as a finish."""
        with self._lock:
            self._window.append(False)
            good = sum(self._window) / len(self._window)
            self._roll_tenant(req, False)
        _stats.inc("slo.finished")
        _stats.inc("slo.errors")
        _stats.set_gauge("slo.goodput", round(good, 4))
        _stats.set_gauge("slo.burn_rate", round(self._burn(good), 3))
        req.slo_ok = False

    # ---------------- per-tenant windows (ISSUE 17) ----------------

    def _roll_tenant(self, req, ok: bool) -> None:
        """Roll the verdict into the request's tenant window (lock
        held by the caller). Requests without a tenant cost exactly
        one attribute read; past ``tenant_windows_max`` tenants the
        overflow shares one ``__other__`` window — the cardinality
        bound. Publishes the worst tenant's rolling goodput as the
        ``tenant.min_goodput`` gauge (the fairness dashboard row)."""
        t = getattr(req, "tenant", None)
        if t is None:
            return
        w = self._tenant_windows.get(t)
        if w is None:
            if len(self._tenant_windows) >= self.tenant_windows_max:
                t = "__other__"
                w = self._tenant_windows.get(t)
            if w is None:
                w = self._tenant_windows[t] = deque(
                    maxlen=self._window.maxlen)
        w.append(ok)
        worst = min(sum(win) / len(win)
                    for win in self._tenant_windows.values() if win)
        _stats.set_gauge("tenant.min_goodput", round(worst, 4))

    def tenant_goodputs(self) -> dict:
        """Rolling goodput per tenant window (only tenants that have
        finished at least one request appear)."""
        with self._lock:
            return {t: sum(w) / len(w)
                    for t, w in self._tenant_windows.items() if w}

    def tenant_burn_rates(self) -> dict:
        return {t: self._burn(g)
                for t, g in self.tenant_goodputs().items()}

    @property
    def tenant_min_goodput(self):
        """Worst tenant's rolling goodput (None before any tenant-
        stamped finish)."""
        g = self.tenant_goodputs()
        return min(g.values()) if g else None

    # ---------------- rolling views ----------------

    def _burn(self, goodput: float) -> float:
        return (1.0 - goodput) / max(1.0 - self.objective, 1e-9)

    @property
    def goodput(self) -> Optional[float]:
        """Rolling fraction of finished requests meeting both targets
        (None before any finish)."""
        with self._lock:
            if not self._window:
                return None
            return sum(self._window) / len(self._window)

    @property
    def burn_rate(self) -> Optional[float]:
        g = self.goodput
        return None if g is None else self._burn(g)

    def update_gauges(self, queue_depth: int, active: int,
                      prefilling: int, slots: int) -> None:
        """Refresh the load gauges (scheduler, once per step)."""
        _stats.set_gauge("slo.queue_depth", queue_depth)
        _stats.set_gauge("slo.slot_occupancy",
                         (active + prefilling) / max(slots, 1))

    def reset(self) -> None:
        """Forget the rolling windows (bench warmup boundary)."""
        with self._lock:
            self._window.clear()
            self._tenant_windows.clear()
