"""paddle.signal — framing + STFT/ISTFT.

TPU-native equivalent of the reference's signal module (reference:
python/paddle/signal.py — frame:30, overlap_add:145, stft:246,
istft:423 over phi frame/overlap_add kernels + fft). Complex spectra
ride the CPU-offload path shared with paddle.fft (the TPU backend has
no complex dtypes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops.dispatch import as_tensor_args, eager_apply

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_raw(a, frame_length: int, hop_length: int):
    """[..., T] -> [..., n_frames, frame_length] strided frames (shared
    by frame/stft and audio.features)."""
    n = a.shape[-1]
    if frame_length > n:
        raise ValueError(f"frame_length {frame_length} > signal "
                         f"length {n}")
    n_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(n_frames)[:, None])
    return a[..., idx]


def _overlap_add_raw(frames, hop_length: int):
    """[..., n_frames, L] -> [..., L + hop*(n_frames-1)] scatter-add
    (shared by overlap_add and istft)."""
    n_frames, frame_length = frames.shape[-2], frames.shape[-1]
    total = frame_length + hop_length * (n_frames - 1)
    lead = frames.shape[:-2]
    flat = frames.reshape((-1, n_frames, frame_length))
    pos = (hop_length * jnp.arange(n_frames)[:, None]
           + jnp.arange(frame_length)[None, :])
    out = jnp.zeros((flat.shape[0], total), flat.dtype)
    out = out.at[:, pos].add(flat)
    return out.reshape(lead + (total,)), pos


def frame(x, frame_length: int, hop_length: int, axis: int = -1,
          name=None):
    """Slide overlapping frames of ``frame_length`` every ``hop_length``
    (reference: signal.py frame:30). axis=-1: [..., T] → [..., F, L];
    axis=0: [T, ...] → [L, F, ...] matching the reference layout."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    (t,) = as_tensor_args(x)

    def raw(a):
        if axis not in (-1, a.ndim - 1, 0):
            raise ValueError("axis must be 0 or -1")
        move = axis == 0 and a.ndim > 1
        if move:
            a = jnp.moveaxis(a, 0, -1)
        out = _frame_raw(a, frame_length, hop_length)  # [..., F, L]
        if axis == 0:
            out = jnp.moveaxis(out, (-2, -1), (1, 0)) if a.ndim > 1 \
                else jnp.swapaxes(out, -1, -2)
        return out

    return eager_apply("frame", raw, [t])


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of frame: sum overlapping frames (reference:
    signal.py overlap_add:145). axis=-1: [..., F, L] → [..., T]."""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    (t,) = as_tensor_args(x)

    def raw(a):
        if axis not in (-1, a.ndim - 1, 0):
            raise ValueError("axis must be 0 or -1")
        if axis == 0:
            a = jnp.moveaxis(a, (0, 1), (-1, -2)) if a.ndim > 2 \
                else jnp.swapaxes(a, 0, 1)
        out, _ = _overlap_add_raw(a, hop_length)
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out

    return eager_apply("overlap_add", raw, [t])


def _prepare_window(window, win_length: int, n_fft: int):
    """Build/center-pad the analysis window ON THE CPU DEVICE (the
    frames it multiplies are CPU-committed; a TPU-committed window
    would be a committed-device mismatch)."""
    cpu = jax.devices("cpu")[0]
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = window._data if hasattr(window, "_data") \
            else jnp.asarray(window)
    if win_length > n_fft:
        raise ValueError(f"win_length {win_length} > n_fft {n_fft}")
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    return jax.device_put(win, cpu)


def stft(x, n_fft: int, hop_length=None, win_length=None, window=None,
         center: bool = True, pad_mode: str = "reflect",
         normalized: bool = False, onesided: bool = True, name=None):
    """Short-time Fourier transform (reference: signal.py stft:246).
    x: [..., T] real → [..., n_fft//2+1 (onesided), n_frames] complex."""
    from .fft import to_cpu_op

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = _prepare_window(window, win_length, n_fft)

    (t,) = as_tensor_args(x)
    t = to_cpu_op(t)

    def raw(sig):
        if center:
            pad = [(0, 0)] * (sig.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            sig = jnp.pad(sig, pad, mode=pad_mode)
        frames = _frame_raw(sig, n_fft, hop_length) * win
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., bins, frames]

    with jax.default_device(jax.devices("cpu")[0]):
        return eager_apply("stft", raw, [t])


def istft(x, n_fft: int, hop_length=None, win_length=None, window=None,
          center: bool = True, normalized: bool = False,
          onesided: bool = True, length=None, return_complex: bool = False,
          name=None):
    """Inverse STFT with window-envelope normalization (reference:
    signal.py istft:423)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if onesided and return_complex:
        raise ValueError("return_complex=True requires onesided=False "
                         "(a onesided spectrum reconstructs a real "
                         "signal; reference istft errors likewise)")
    win = _prepare_window(window, win_length, n_fft)

    (t,) = as_tensor_args(x)

    def raw(spec):
        spec = jnp.swapaxes(spec, -1, -2)  # [..., frames, bins]
        if normalized:
            spec = spec * jnp.sqrt(n_fft)
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        n_frames = frames.shape[-2]
        out, pos = _overlap_add_raw(frames, hop_length)
        total = out.shape[-1]
        # window-envelope normalization (COLA correction)
        env = jnp.zeros((total,), win.dtype)
        env = env.at[pos.reshape(-1)].add(
            jnp.tile(win * win, n_frames))
        out = out / jnp.maximum(env, 1e-10)
        if center:
            out = out[..., n_fft // 2: total - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    with jax.default_device(jax.devices("cpu")[0]):
        return eager_apply("istft", raw, [t])
