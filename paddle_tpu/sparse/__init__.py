"""paddle_tpu.sparse — COO/CSR sparse tensors + sparse ops.

TPU-native equivalent of the reference's sparse package (reference:
python/paddle/sparse — sparse_coo_tensor creation/creation.py, CSR
variant, unary/binary/matmul ops backed by
paddle/phi/kernels/sparse/*). The TPU design rides
``jax.experimental.sparse.BCOO`` — XLA's batched-COO format whose
matmuls lower to gather/segment-sum programs the TPU pipelines well —
instead of hand-written scatter kernels; CSR is stored natively and
converted to COO for compute.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "matmul", "add", "multiply", "relu", "nn",
    "is_sparse_coo", "is_sparse_csr",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor (reference: phi SparseCooTensor,
    paddle/phi/core/sparse_coo_tensor.h). indices(): [sparse_ndim, nnz]."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self.shape) != 2:
            raise ValueError("to_sparse_csr supports 2-D tensors")
        rows = np.asarray(self._bcoo.indices[:, 0])
        order = np.argsort(rows, kind="stable")
        crows = np.zeros(self.shape[0] + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(
            jnp.asarray(crows),
            jnp.asarray(np.asarray(self._bcoo.indices[:, 1])[order]),
            jnp.asarray(np.asarray(self._bcoo.data)[order]), self.shape)

    def __matmul__(self, other):
        return matmul(self, other)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor (reference: phi SparseCsrTensor,
    paddle/phi/core/sparse_csr_tensor.h)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = _arr(crows)
        self._cols = _arr(cols)
        self._values = _arr(values)
        self._shape = [int(s) for s in shape]

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def nnz(self) -> int:
        return int(self._values.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return Tensor(self._values)

    def to_sparse_coo(self) -> SparseCooTensor:
        counts = jnp.diff(self._crows)
        rows = jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.nnz())
        idx = jnp.stack([rows, self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=tuple(self._shape)))

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Create a COO tensor (reference: sparse/creation.py
    sparse_coo_tensor). indices: [sparse_ndim, nnz]."""
    idx = _arr(indices).astype(jnp.int32)
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype).np_dtype)
    idx_t = jnp.swapaxes(idx, 0, 1)  # BCOO wants [nnz, ndim]
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1)))
    return SparseCooTensor(
        jsparse.BCOO((vals, idx_t), shape=tuple(int(s) for s in shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Create a CSR tensor (reference: sparse/creation.py
    sparse_csr_tensor)."""
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype).np_dtype)
    return SparseCsrTensor(_arr(crows).astype(jnp.int64),
                           _arr(cols).astype(jnp.int64), vals, shape)


def is_sparse_coo(x) -> bool:
    return isinstance(x, SparseCooTensor)


def is_sparse_csr(x) -> bool:
    return isinstance(x, SparseCsrTensor)


def _as_bcoo(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()._bcoo
    return None


def matmul(x, y):
    """sparse @ dense → dense (reference: sparse/binary.py matmul,
    phi/kernels/sparse/matmul_kernel.h). Lowers to BCOO dot_general —
    a gather + segment-sum XLA program."""
    xs, ys = _as_bcoo(x), _as_bcoo(y)
    if xs is not None and ys is None:
        return Tensor(xs @ _arr(y))
    if xs is None and ys is not None:
        return Tensor(_arr(x) @ ys)
    if xs is not None and ys is not None:
        return Tensor(xs @ ys.todense())
    return Tensor(_arr(x) @ _arr(y))


def add(x, y):
    """sparse + sparse → sparse (duplicate indices summed);
    sparse + dense → dense."""
    xs, ys = _as_bcoo(x), _as_bcoo(y)
    if xs is not None and ys is not None:
        summed = jsparse.BCOO(
            (jnp.concatenate([xs.data, ys.data]),
             jnp.concatenate([xs.indices, ys.indices])),
            shape=xs.shape).sum_duplicates(nse=xs.nse + ys.nse)
        return SparseCooTensor(summed)
    if xs is not None:
        return Tensor(xs.todense() + _arr(y))
    if ys is not None:
        return Tensor(_arr(x) + ys.todense())
    return Tensor(_arr(x) + _arr(y))


def multiply(x, y):
    """Elementwise multiply. sparse * dense keeps the sparsity pattern
    (dense entries gathered at the nonzeros)."""
    xs = _as_bcoo(x)
    if xs is None:
        ys = _as_bcoo(y)
        if ys is not None:  # dense * sparse — sparsity wins either way
            return multiply(y, x)
        return Tensor(_arr(x) * _arr(y))
    other = _as_bcoo(y)
    dense = other.todense() if other is not None else _arr(y)
    gathered = dense[tuple(xs.indices[:, i]
                           for i in range(xs.indices.shape[1]))]
    return SparseCooTensor(jsparse.BCOO(
        (xs.data * gathered, xs.indices), shape=xs.shape))


def relu(x):
    """Unary op on values only (reference: sparse/unary.py relu —
    sparsity pattern is preserved)."""
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(jsparse.BCOO(
            (jax.nn.relu(x._bcoo.data), x._bcoo.indices),
            shape=x._bcoo.shape))
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x._crows, x._cols,
                               jax.nn.relu(x._values), x._shape)
    return Tensor(jax.nn.relu(_arr(x)))


from . import nn  # noqa: E402,F401  (real sparse.nn module)
